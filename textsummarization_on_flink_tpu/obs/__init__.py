"""Unified runtime observability layer (ISSUE 1 tentpole).

One process-wide registry of counters/gauges/histograms, lightweight
nesting span tracing, and exporters (unified JSONL events, Chrome-trace
dump, Prometheus-style text exposition).  Every layer of the stack —
train loop, data pipeline, beam decoder, streaming pipeline, checkpoint
IO — reports through this module; see OBSERVABILITY.md for the metric
naming scheme (``<layer>/<name>``) and the full inventory.

Usage:

    from textsummarization_on_flink_tpu import obs

    obs.counter("decode/tokens_total").inc(n)
    obs.gauge("train/prefetch_queue_depth").set(q.qsize())
    obs.histogram("decode/request_latency_seconds").observe(dt)
    with obs.span("decode/batch"):
        ...
    print(obs.render_text())          # Prometheus-style exposition
    obs.snapshot(compact=True)        # dict dump (BENCH row embedding)

Disabling: ``TS_OBS=0`` in the environment kills the default registry
for the whole process (instrumented code receives shared null metrics
— near-zero cost); per-job, ``HParams(obs=False)`` makes
``registry_for(hps)`` hand back the null registry so one component can
run dark while others report.  Dependency-light by design: importing
this package never imports jax/numpy.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional, Sequence

from textsummarization_on_flink_tpu.obs.export import (
    EventSink,
    install_event_sink as _install_event_sink,
    snapshot_event,
    write_chrome_trace as _write_chrome_trace,
)
from textsummarization_on_flink_tpu.obs.registry import (
    Counter,
    DEFAULT_TIME_BUCKETS,
    Gauge,
    Histogram,
    NULL_COUNTER,
    NULL_GAUGE,
    NULL_HISTOGRAM,
    NULL_REGISTRY,
    Registry,
    exponential_buckets,
)
from textsummarization_on_flink_tpu.obs.spans import (
    NULL_SPAN,
    SpanRecord,
    TraceContext,
    Tracer,
    request_event as _request_event,
    span as _span,
    tracer_for,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "Tracer", "SpanRecord",
    "TraceContext", "EventSink", "NULL_REGISTRY", "NULL_COUNTER",
    "NULL_GAUGE", "NULL_HISTOGRAM", "NULL_SPAN", "DEFAULT_TIME_BUCKETS",
    "exponential_buckets", "enabled_from_env", "registry", "registry_for",
    "set_default_registry", "use_registry", "counter", "gauge", "histogram",
    "span", "request_event", "render_text", "snapshot", "snapshot_event",
    "install_event_sink", "write_chrome_trace", "tracer_for", "heartbeat",
    "install_flight_recorder", "serve_http", "install_profiler",
    "profiler_for",
]

_default: Optional[Registry] = None
_default_lock = threading.Lock()


def enabled_from_env() -> bool:
    """TS_OBS gate: unset/1/on/true/yes -> enabled; 0/off/false/no -> off."""
    return os.environ.get("TS_OBS", "1").lower() not in (
        "0", "off", "false", "no")


def registry() -> Registry:
    """The process-wide default registry (created on first use; honors
    TS_OBS at creation time)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Registry(enabled=enabled_from_env())
    return _default


def set_default_registry(reg: Optional[Registry]) -> Registry:
    """Swap the process default (None re-resolves TS_OBS on next use).
    Returns the previous default (possibly None -> the new lazy one)."""
    global _default
    with _default_lock:
        prev, _default = _default, reg
    return prev if prev is not None else registry()


class use_registry:
    """Context manager: route the module facade through `reg` (tests)."""

    def __init__(self, reg: Registry):
        self._reg = reg
        self._prev: Optional[Registry] = None

    def __enter__(self) -> Registry:
        global _default
        with _default_lock:
            self._prev = _default
            _default = self._reg
        return self._reg

    def __exit__(self, exc_type, exc, tb) -> None:
        global _default
        with _default_lock:
            _default = self._prev


def registry_for(hps: Any) -> Registry:
    """The registry a component should report through: the process
    default, unless the job's HParams carries obs=False (or the default
    itself is disabled)."""
    if hps is not None and not getattr(hps, "obs", True):
        return NULL_REGISTRY
    return registry()


# -- module-level conveniences (route through the default registry) --

def counter(name: str) -> Counter:
    return registry().counter(name)


def gauge(name: str) -> Gauge:
    return registry().gauge(name)


def histogram(name: str, buckets: Optional[Sequence[float]] = None,
              ) -> Histogram:
    return registry().histogram(name, buckets)


def span(name: str, parent: Optional["TraceContext"] = None, **attrs: Any):
    return _span(registry(), name, parent=parent, **attrs)


def request_event(event: str, ctx: Optional["TraceContext"], uuid: str,
                  **attrs: Any) -> bool:
    return _request_event(registry(), event, ctx, uuid, **attrs)


def heartbeat(name: str, period: float = 10.0) -> None:
    """Record a component liveness beat on the default registry's
    heartbeat board (`/healthz` flips it degraded when stale — the live
    exposition plane, obs/http.py).  Lazy import keeps obs itself free
    of http.server until someone actually beats or serves."""
    from textsummarization_on_flink_tpu.obs import http as http_mod

    http_mod.heartbeat(registry(), name, period=period)


def install_flight_recorder(directory: str, capacity: Optional[int] = None,
                            reg: Optional[Registry] = None):
    """Attach a failure flight recorder (obs/flightrec.py) to `reg` (the
    default registry when None); returns it, or None when disabled.
    ``capacity`` follows the HParams.flight_frames convention: None =
    the module default ring, 0 = disabled (returns None)."""
    if capacity == 0:
        return None
    from textsummarization_on_flink_tpu.obs import flightrec as flight_mod

    kw = {"capacity": capacity} if capacity is not None else {}
    return flight_mod.install_flight_recorder(
        reg if reg is not None else registry(), directory, **kw)


def install_profiler(reg: Optional[Registry] = None, **kw: Any):
    """Attach the performance attribution plane (obs/profile.py, ISSUE
    16) to `reg` (default registry when None): phase ledger + compile
    ledger + divergence sentinel, exposed on /profile.  First install
    wins; kwargs (clock, divergence_factor) thread to the Profiler."""
    from textsummarization_on_flink_tpu.obs import profile as profile_mod

    return profile_mod.install_profiler(
        reg if reg is not None else registry(), **kw)


def profiler_for(reg: Optional[Registry] = None):
    """The registry's profiler (obs/profile.py), or the shared null
    profiler for a dark registry — safe to call on every dispatch."""
    from textsummarization_on_flink_tpu.obs import profile as profile_mod

    return profile_mod.profiler_for(reg if reg is not None else registry())


def serve_http(port: int, reg: Optional[Registry] = None):
    """Start the live exposition plane (obs/http.py) on 127.0.0.1:port
    over `reg` (default registry when None); returns the server."""
    from textsummarization_on_flink_tpu.obs import http as http_mod

    return http_mod.ObsHttpServer(
        reg if reg is not None else registry(), port=port).start()


def render_text(exemplars: Optional[bool] = None,
                openmetrics: bool = False) -> str:
    return registry().render_text(exemplars=exemplars, openmetrics=openmetrics)


def snapshot(compact: bool = False) -> Dict[str, Dict]:
    return registry().snapshot(compact=compact)


def install_event_sink(directory: str, flush_secs: float = 2.0,
                       max_queue: int = 4096,
                       reg: Optional[Registry] = None) -> Optional[EventSink]:
    return _install_event_sink(reg if reg is not None else registry(),
                               directory, flush_secs=flush_secs,
                               max_queue=max_queue)


def write_chrome_trace(path: str, reg: Optional[Registry] = None) -> int:
    return _write_chrome_trace(reg if reg is not None else registry(), path)
