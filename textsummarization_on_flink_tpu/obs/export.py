"""Telemetry exporters: unified JSONL event sink + Chrome-trace dump.

The EventSink is the only writer that may sit on a hot path, so it is
built to never block or crash the caller:

  * `emit()` is a bounded-queue put_nowait — a full queue increments
    ``obs/events_dropped_total`` and drops the record (telemetry must
    never stall a train step);
  * a daemon flusher thread batches queued records to disk every
    `flush_secs`;
  * a deleted/rotated target directory is recreated and the file
    reopened; a persistent write failure increments
    ``obs/sink_write_errors_total`` and drops the batch (same contract
    as SummaryWriter.scalars, ISSUE 1 satellite 2).

File format: one JSON object per line under
``<log_root>/<exp>/<job>/events.jsonl`` — the SAME file family
SummaryWriter uses for scalars (`{"step": N, ...}`); obs records carry a
``"kind"`` discriminator ({"kind": "span" | "snapshot"}), so one reader
(scripts/trace_summary.py) summarizes both.
"""

from __future__ import annotations

import json
import os
import queue
import threading
from typing import Any, Dict, List, Optional

from textsummarization_on_flink_tpu.obs import spans as spans_lib
from textsummarization_on_flink_tpu.obs.registry import Registry

EVENTS_FILENAME = "events.jsonl"


class EventSink:
    """Bounded-queue background JSONL writer."""

    def __init__(self, directory: str, filename: str = EVENTS_FILENAME,
                 flush_secs: float = 2.0, max_queue: int = 4096,
                 registry: Optional[Registry] = None):
        self.directory = directory
        self.path = os.path.join(directory, filename)
        self._flush_secs = max(flush_secs, 0.05)
        self._q: "queue.Queue[Optional[dict]]" = queue.Queue(maxsize=max_queue)
        reg = registry if registry is not None else Registry(enabled=True)
        self._dropped = reg.counter("obs/events_dropped_total")
        self._write_errors = reg.counter("obs/sink_write_errors_total")
        # gap annotation (ISSUE 9 satellite): drops since the last flush
        # cycle, folded into the stream as one {"kind": "drops"} record
        # so a hole in events.jsonl is visible IN the file, not only in
        # the counter.  Own lock: touched only on the (already
        # overloaded) drop path and once per flush cycle.
        self._drop_note_lock = threading.Lock()
        self._pending_drops = 0
        self._registry = reg
        self._f = None
        self._closed = threading.Event()
        self._kick = threading.Event()  # close()/flush() fast-forward
        # flush-cycle generation: bumped by the flusher after each
        # drain+write completes, so flush() can wait for a write that
        # STARTED after it was called instead of sleeping and hoping
        self._gen = 0
        self._gen_cv = threading.Condition()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="obs-event-sink")
        self._thread.start()

    # -- producer side (any thread, never blocks) --
    def emit(self, record: Dict[str, Any]) -> bool:
        """Queue one record; False (+ drop counter) when the queue is
        full or the sink is closed."""
        if self._closed.is_set():
            self._dropped.inc()
            self._note_drop()
            return False
        try:
            self._q.put_nowait(record)
            return True
        except queue.Full:
            self._dropped.inc()
            self._note_drop()
            return False

    def _note_drop(self) -> None:
        with self._drop_note_lock:
            self._pending_drops += 1

    # -- flusher --
    def _open(self) -> bool:
        try:
            os.makedirs(self.directory, exist_ok=True)
            self._f = open(self.path, "a", encoding="utf-8")
            return True
        except OSError:
            self._f = None
            return False

    def _write_batch(self, batch: List[dict]) -> None:
        if not batch:
            return
        payload = "".join(json.dumps(r) + "\n" for r in batch)
        # a rotated/deleted directory does NOT fail writes on POSIX (the
        # unlinked inode absorbs them) — detect it by path and reopen.
        # One stat per flush batch, never on the emit hot path.
        if self._f is not None and not os.path.exists(self.path):
            try:
                self._f.close()
            except (OSError, ValueError):  # double-close on a dead handle
                pass
            self._f = None
        for attempt in (0, 1):
            if self._f is None and not self._open():
                continue
            try:
                self._f.write(payload)
                self._f.flush()
                return
            except (OSError, ValueError):  # ValueError: closed file
                try:
                    self._f.close()
                except (OSError, ValueError):
                    pass
                self._f = None
        # both attempts failed: count the loss, drop the batch
        self._write_errors.inc(len(batch))

    def _drain(self) -> List[dict]:
        batch: List[dict] = []
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return batch
            if item is not None:
                batch.append(item)

    def _bump_gen(self) -> None:
        with self._gen_cv:
            self._gen += 1
            self._gen_cv.notify_all()

    def _annotated(self, batch: List[dict]) -> List[dict]:
        """Fold any drop episode since the last cycle into the stream as
        one ``{"kind": "drops", "count": N}`` record.  The drops
        happened because the queue was full of exactly the records being
        drained now, so the hole sits AFTER them in file order (a
        best-effort position — racing emits may interleave)."""
        with self._drop_note_lock:
            n, self._pending_drops = self._pending_drops, 0
        if n:
            import time as _t

            batch.append({"kind": "drops", "count": n,
                          "ts_us": int(_t.time() * 1e6)})
        return batch

    def _run(self) -> None:
        from textsummarization_on_flink_tpu.obs import http as http_mod

        period = max(self._flush_secs, 1.0)
        while not self._closed.is_set():
            # the flusher is a component of the live plane: its own
            # heartbeat makes a wedged sink visible on /healthz
            http_mod.heartbeat(self._registry, "obs/event_sink",
                               period=period)
            self._kick.wait(self._flush_secs)
            self._kick.clear()
            self._write_batch(self._annotated(self._drain()))
            self._bump_gen()
        self._write_batch(self._annotated(self._drain()))  # final flush
        self._bump_gen()
        # clean shutdown: a closed sink must not hold /healthz degraded
        http_mod.retire_heartbeat(self._registry, "obs/event_sink")
        if self._f is not None:
            try:
                self._f.close()
            except (OSError, ValueError):  # flusher exit: best-effort close
                pass

    def flush(self, timeout: float = 5.0) -> None:
        """Wait (bounded) until a drain+write cycle that STARTED after
        this call has completed — everything emitted before the call is
        then on disk (or counted dropped), not merely dequeued."""
        import time as _t

        deadline = _t.monotonic() + timeout
        with self._gen_cv:
            # +2: the current cycle may have drained the queue before our
            # caller's records were enqueued; two completions guarantee a
            # full cycle ran start-to-finish after this point
            target = self._gen + 2
            while self._gen < target and self._thread.is_alive():
                remaining = deadline - _t.monotonic()
                if remaining <= 0:
                    break
                self._kick.set()
                self._gen_cv.wait(min(remaining, 0.05))

    def close(self, timeout: float = 5.0) -> None:
        if self._closed.is_set():
            return
        self.flush(timeout)
        self._closed.set()
        self._kick.set()
        self._thread.join(timeout=timeout)


class MemorySink:
    """In-memory EventSink stand-in (same ``emit`` contract): records
    land in a bounded list instead of a file.  For tests and for
    bench.py's trace-derived per-request breakdown, where spinning a
    flusher thread and parsing JSONL back would only add noise."""

    def __init__(self, max_records: int = 100_000):
        self._lock = threading.Lock()
        self._records: List[dict] = []
        self._max = max_records

    def emit(self, record: Dict[str, Any]) -> bool:
        with self._lock:
            if len(self._records) >= self._max:
                return False
            self._records.append(record)
            return True

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._records)

    def flush(self, timeout: float = 0.0) -> None:
        pass  # synchronous by construction

    def close(self, timeout: float = 0.0) -> None:
        pass


def install_event_sink(registry: Registry, directory: str,
                       flush_secs: float = 2.0,
                       max_queue: int = 4096) -> Optional[EventSink]:
    """Attach an EventSink to `registry` so finished spans stream to
    `<directory>/events.jsonl`.  No-op (None) on a disabled registry."""
    if not registry.enabled:
        return None
    sink = EventSink(directory, flush_secs=flush_secs, max_queue=max_queue,
                     registry=registry)
    registry.event_sink = sink
    return sink


def write_chrome_trace(registry: Registry, path: str) -> int:
    """Dump the registry's buffered spans as a Chrome-trace JSON file
    (`{"traceEvents": [...]}`) — the dialect scripts/trace_summary.py
    already summarizes.  Returns the number of span events written."""
    tracer = spans_lib.tracer_for(registry)
    events = tracer.chrome_trace_events()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"traceEvents": events}, f)
    return sum(1 for e in events if e.get("ph") == "X")


def snapshot_event(registry: Registry, compact: bool = True,
                   ) -> Dict[str, Any]:
    """A `{"kind": "snapshot", "metrics": {...}}` record for the unified
    events.jsonl (periodic registry dumps alongside spans/scalars)."""
    return {"kind": "snapshot", "metrics": registry.snapshot(compact=compact)}
