"""Performance attribution plane (ISSUE 16; OBSERVABILITY.md
"Performance attribution").

Three ledgers behind one per-registry ``Profiler`` (attached at
``registry.profile``, first-install-wins like the SLO engine):

  * **phase ledger** — dispatch-boundary timers around the serve and
    train hot paths (prefill / pack / decode chunk / harvest / evict in
    the continuous path, per-tier micro-batch dispatch, and the train
    loop's host-wait / step-dispatch / metrics-flush / checkpoint
    sub-phases), aggregated into the labeled ``profile/phase_seconds``
    histogram plus a phases-sum-to-wall accounting check
    (``profile/phase_coverage_ratio``).  The clock is injectable so the
    tier-1 gate drives it in virtual time.
  * **compile ledger** — the ONE shared jit-cache-diff helper
    (``compiled_call``) the decode paths route through, recording every
    compile event (site, shape/bucket key, wall duration, warm-set
    size) and firing a ``compile_storm`` flight dump + /alerts entry
    when a site's compile count exceeds its committed budget (warm set
    = 4 decode kernels + one prefill per bucket + one spec kernel per
    k).  The compile-once invariant becomes runtime-monitored, not just
    test-pinned.
  * **divergence sentinel** — per dispatch shape, the executed
    program's analytic cost (``__graft_entry__.decode_step_cost`` /
    ``prefill_cost`` / ``train_step_cost``) is priced ONCE off the hot
    path (the helpers AOT-compile, so pricing runs on a daemon thread;
    ``hps.profile_analytic`` gates it); each dispatch then publishes
    achieved bytes/s and FLOPs/s gauges and fires a ``perf_divergence``
    flight dump when throughput drops below the warm per-shape baseline
    by more than ``hps.profile_divergence_factor``.

Exposition: ``profile_payload(registry)`` backs the read-only
``/profile`` endpoint (phase table, compile ledger, top-k slowest
dispatches with trace exemplar ids for scripts/trace_summary.py);
``profile_alerts(registry)`` rides the /alerts scrape.  Both serve
state cached on the record side — a scrape never mutates or pays dump
I/O (the /alerts discipline from obs/slo.py).

Null path: a dark registry (``hps.obs=False``) gets the shared
``NULL_PROFILER`` whose methods return constants — no per-dispatch
allocation (pinned in tests/test_profile.py).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from textsummarization_on_flink_tpu.obs import flightrec
from textsummarization_on_flink_tpu.obs.registry import Registry

#: bounded ring of recent phase records — feeds the /profile top-k
#: slowest-dispatch table and the windowed coverage check in tests
RECENT_PHASES_CAP = 512
#: bounded compile-event history for /profile
COMPILE_EVENTS_CAP = 256
#: ledger notes (profiler captures, budget registrations) kept
NOTES_CAP = 64
#: dispatches that establish a shape's warm throughput baseline before
#: the divergence sentinel starts judging (the first dispatch carries
#: the compile, so the baseline is the BEST of the first N, not the
#: first)
BASELINE_SAMPLES = 3
#: default measured-vs-baseline wall inflation that fires the
#: ``perf_divergence`` dump (overridden by hps.profile_divergence_factor)
DEFAULT_DIVERGENCE_FACTOR = 5.0


class _NullProfiler:
    """Shared do-nothing profiler for dark registries: every method
    returns a preexisting constant, so the ``obs=False`` path adds no
    per-dispatch allocation (the null-object contract of
    NULL_COUNTER/NULL_GAUGE — pinned by test_profile)."""

    __slots__ = ()

    def start(self) -> float:
        return 0.0

    def end(self, phase, t0, trace_id=None) -> float:
        return 0.0

    def end_wall(self, name, t0) -> float:
        return 0.0

    def set_compile_budget(self, site, budget) -> None:
        pass

    def record_compile(self, site, key, dur_s) -> None:
        pass

    def record_hit(self, site) -> None:
        pass

    def register_cost(self, site, key, provider) -> None:
        pass

    def prime_cost(self, site, key, flops, bytes_) -> None:
        pass

    def observe_dispatch(self, site, key, wall_s, trace_id=None) -> None:
        pass

    def note(self, kind, **fields) -> None:
        pass

    def phase_stats(self) -> Dict[str, Tuple[int, float, float]]:
        return {}

    def compile_stats(self) -> Dict[str, Dict[str, Any]]:
        return {}

    def coverage(self) -> float:
        return 0.0


NULL_PROFILER = _NullProfiler()


class Profiler:
    """Per-registry performance attribution state (phase ledger +
    compile ledger + divergence sentinel).  All record paths run on
    dispatch threads, so they take one short lock, touch no device
    values, and never raise past telemetry."""

    def __init__(self, registry: Registry,
                 clock: Callable[[], float] = time.perf_counter,
                 divergence_factor: float = DEFAULT_DIVERGENCE_FACTOR):
        self._reg = registry
        self._clock = clock
        self._div_factor = max(float(divergence_factor), 1.0)
        self._lock = threading.Lock()
        # phase ledger: name -> [count, total_s, max_s]; walls likewise
        self._phases: Dict[str, List[float]] = {}
        self._walls: Dict[str, List[float]] = {}
        self._recent: List[Tuple[int, str, float, Optional[str]]] = []
        # compile ledger: site -> {compiles, hits, keys, last_dur_s}
        self._sites: Dict[str, Dict[str, Any]] = {}
        self._budgets: Dict[str, int] = {}
        self._compile_events: List[Dict[str, Any]] = []
        self._storm: Optional[Dict[str, Any]] = None
        # divergence sentinel: (site, key) -> cost/baseline state
        self._costs: Dict[Tuple[str, Any], Dict[str, float]] = {}
        self._pricing: set = set()
        self._div: Dict[Tuple[str, Any], Dict[str, float]] = {}
        self._notes: List[Dict[str, Any]] = []
        # metric families (literal names — the doc-drift gate reads the
        # source): children are created per label value at record time
        self._h_phase = registry.histogram("profile/phase_seconds")
        self._h_wall = registry.histogram("profile/wall_seconds")
        self._g_coverage = registry.gauge("profile/phase_coverage_ratio")
        self._c_compiles = registry.counter("profile/compile_events_total")
        self._h_compile = registry.histogram("profile/compile_seconds")
        self._c_storms = registry.counter("profile/compile_storms_total")
        self._g_bps = registry.gauge("profile/achieved_bytes_per_second")
        self._g_fps = registry.gauge("profile/achieved_flops_per_second")
        self._c_div = registry.counter("profile/divergence_dumps_total")

    # -- phase ledger ---------------------------------------------------
    def start(self) -> float:
        """A phase/wall start token (the injected clock's now)."""
        return self._clock()

    def end(self, phase: str, t0: float,
            trace_id: Optional[str] = None) -> float:
        """Close one phase opened by start(); returns its duration."""
        dt = self._clock() - t0
        ts_us = int(time.time() * 1e6)  # serialized epoch stamp only
        with self._lock:
            agg = self._phases.get(phase)
            if agg is None:
                agg = self._phases[phase] = [0, 0.0, 0.0]
            agg[0] += 1
            agg[1] += dt
            if dt > agg[2]:
                agg[2] = dt
            self._recent.append((ts_us, phase, dt, trace_id))
            if len(self._recent) > RECENT_PHASES_CAP:
                del self._recent[:len(self._recent) - RECENT_PHASES_CAP]
        self._h_phase.labels(phase=phase).observe(dt, trace_id=trace_id)
        return dt

    def end_wall(self, name: str, t0: float) -> float:
        """Close one WALL unit (a serve tick, a train round) — the
        denominator of the phases-sum-to-wall accounting check."""
        dt = self._clock() - t0
        with self._lock:
            agg = self._walls.get(name)
            if agg is None:
                agg = self._walls[name] = [0, 0.0, 0.0]
            agg[0] += 1
            agg[1] += dt
            if dt > agg[2]:
                agg[2] = dt
            cov = self._coverage_locked()
        self._h_wall.labels(wall=name).observe(dt)
        self._g_coverage.set(cov)
        return dt

    def _coverage_locked(self) -> float:
        wall = sum(w[1] for w in self._walls.values())
        if wall <= 0.0:
            return 0.0
        return sum(p[1] for p in self._phases.values()) / wall

    def coverage(self) -> float:
        """sum(phase time) / sum(wall time) — the accounting check."""
        with self._lock:
            return self._coverage_locked()

    def phase_stats(self) -> Dict[str, Tuple[int, float, float]]:
        """{phase: (count, total_s, max_s)} snapshot (bench evidence
        fields diff this across the timed window)."""
        with self._lock:
            return {k: (int(v[0]), v[1], v[2])
                    for k, v in self._phases.items()}

    def recent_phases(self) -> List[Tuple[int, str, float, Optional[str]]]:
        """Copy of the bounded (ts_us, phase, dur_s, trace_id) ring."""
        with self._lock:
            return list(self._recent)

    # -- compile ledger -------------------------------------------------
    def set_compile_budget(self, site: str, budget: int) -> None:
        """Commit a site's warm-set budget: compiles beyond it are a
        compile storm (dump + /alerts).  Re-registration keeps the MAX
        so a widened engine never shrinks an already-committed budget."""
        with self._lock:
            prev = self._budgets.get(site)
            if prev is None or budget > prev:
                self._budgets[site] = int(budget)

    def record_hit(self, site: str) -> None:
        with self._lock:
            st = self._site_locked(site)
            st["hits"] += 1

    def _site_locked(self, site: str) -> Dict[str, Any]:
        st = self._sites.get(site)
        if st is None:
            st = self._sites[site] = {"compiles": 0, "hits": 0,
                                      "keys": set(), "last_dur_s": 0.0}
        return st

    def record_compile(self, site: str, key: Any, dur_s: float) -> None:
        """One compile event (a jit-cache MISS observed by
        compiled_call, or reported directly by an engine)."""
        ts_us = int(time.time() * 1e6)
        storm: Optional[Dict[str, Any]] = None
        with self._lock:
            st = self._site_locked(site)
            st["compiles"] += 1
            st["keys"].add(key)
            st["last_dur_s"] = dur_s
            warm = sum(s["compiles"] for s in self._sites.values())
            self._compile_events.append({
                "site": site, "key": str(key), "dur_s": round(dur_s, 6),
                "warm_set": warm, "ts_us": ts_us})
            if len(self._compile_events) > COMPILE_EVENTS_CAP:
                del self._compile_events[
                    :len(self._compile_events) - COMPILE_EVENTS_CAP]
            budget = self._budgets.get(site)
            if budget is not None and st["compiles"] > budget:
                storm = {"site": site, "key": str(key),
                         "compiles": st["compiles"], "budget": budget,
                         "warm_set": warm, "ts_us": ts_us}
                self._storm = storm
        self._c_compiles.labels(site=site).inc()
        self._h_compile.observe(dur_s)
        if storm is not None:
            # trigger OUTSIDE the lock: the dump walks the flight ring
            self._c_storms.inc()
            flightrec.trigger(self._reg, "compile_storm", **storm)

    def compile_stats(self) -> Dict[str, Dict[str, Any]]:
        """{site: {compiles, hits, keys, budget, last_dur_s}} snapshot
        — the one source of truth the warm-set test pins assert
        through."""
        with self._lock:
            return {site: {"compiles": st["compiles"], "hits": st["hits"],
                           "keys": sorted(str(k) for k in st["keys"]),
                           "budget": self._budgets.get(site),
                           "last_dur_s": st["last_dur_s"]}
                    for site, st in self._sites.items()}

    def warm_set_size(self) -> int:
        with self._lock:
            return sum(st["compiles"] for st in self._sites.values())

    # -- divergence sentinel --------------------------------------------
    def prime_cost(self, site: str, key: Any, flops: float,
                   bytes_: float) -> None:
        """Install one shape's analytic cost synchronously (tests and
        callers that already hold the numbers)."""
        with self._lock:
            self._costs[(site, key)] = {"flops": float(flops),
                                        "bytes": float(bytes_)}

    def register_cost(self, site: str, key: Any,
                      provider: Callable[[], Dict[str, float]]) -> None:
        """Price one dispatch shape ONCE, off the hot path: `provider`
        (typically a __graft_entry__ cost helper closure, which
        AOT-compiles) runs on a daemon thread; until it lands the
        sentinel simply stays quiet for that shape.  A failing provider
        leaves the shape unpriced — pricing must never break serving."""
        with self._lock:
            ck = (site, key)
            if ck in self._costs or ck in self._pricing:
                return
            self._pricing.add(ck)

        def _price() -> None:
            try:
                cost = provider()
                flops = float(cost.get("flops", 0.0))
                bytes_ = float(cost.get("bytes", 0.0))
            except Exception:  # tslint: disable=TS005 — analytic pricing is best-effort telemetry; a failed import/compile must not surface
                flops = bytes_ = 0.0
            with self._lock:
                self._pricing.discard(ck)
                if flops > 0.0 or bytes_ > 0.0:
                    self._costs[ck] = {"flops": flops, "bytes": bytes_}

        threading.Thread(target=_price, daemon=True,
                         name=f"profile-pricer-{site}").start()

    def observe_dispatch(self, site: str, key: Any, wall_s: float,
                        trace_id: Optional[str] = None) -> None:
        """One measured dispatch of a priced shape: publish achieved
        throughput (analytic cost / measured wall) and fire the
        ``perf_divergence`` dump when it falls below the warm baseline
        by more than the committed factor."""
        if wall_s <= 0.0:
            return
        fire: Optional[Dict[str, Any]] = None
        with self._lock:
            cost = self._costs.get((site, key))
            if cost is None:
                return
            bps = cost["bytes"] / wall_s
            fps = cost["flops"] / wall_s
            st = self._div.get((site, key))
            if st is None:
                st = self._div[(site, key)] = {"samples": 0,
                                               "baseline_bps": 0.0,
                                               "drift": 1.0}
            st["samples"] += 1
            st["bps"] = bps
            st["fps"] = fps
            st["wall_s"] = wall_s
            if st["samples"] <= BASELINE_SAMPLES:
                # warmup window: the first dispatch carries the compile,
                # so the baseline is the BEST achieved throughput seen
                if bps > st["baseline_bps"]:
                    st["baseline_bps"] = bps
            elif bps * self._div_factor < st["baseline_bps"]:
                st["drift"] = st["baseline_bps"] / max(bps, 1e-12)
                fire = {"site": site, "key": str(key),
                        "wall_s": round(wall_s, 6),
                        "achieved_bytes_per_s": round(bps, 3),
                        "baseline_bytes_per_s": round(st["baseline_bps"], 3),
                        "drift": round(st["drift"], 3),
                        "trace_id": trace_id}
            else:
                st["drift"] = st["baseline_bps"] / max(bps, 1e-12)
        self._g_bps.labels(site=site).set(bps)
        self._g_fps.labels(site=site).set(fps)
        if fire is not None:
            self._c_div.inc()
            flightrec.trigger(self._reg, "perf_divergence", **fire)

    # -- ledger notes ---------------------------------------------------
    def note(self, kind: str, **fields: Any) -> None:
        """A non-metric ledger event (e.g. a jax.profiler capture
        window), kept in a bounded ring for /profile."""
        rec = {"note": kind, "ts_us": int(time.time() * 1e6), **fields}
        with self._lock:
            self._notes.append(rec)
            if len(self._notes) > NOTES_CAP:
                del self._notes[:len(self._notes) - NOTES_CAP]
        # the frame kind is the ring's discriminator; the note's own
        # kind rides as the `note` field
        flightrec.record(self._reg, "profile_note", **rec)

    # -- exposition (read-only snapshots) -------------------------------
    def payload(self, top_k: int = 8) -> Dict[str, Any]:
        """The /profile body: phase table, wall/coverage accounting,
        compile ledger, divergence table, top-k slowest dispatches (with
        trace exemplar ids that paste into scripts/trace_summary.py
        --request), and ledger notes.  Pure read under one lock."""
        with self._lock:
            phases = [{"phase": k, "count": int(v[0]),
                       "total_s": round(v[1], 6), "max_s": round(v[2], 6),
                       "mean_ms": round(1e3 * v[1] / v[0], 3) if v[0]
                       else 0.0}
                      for k, v in sorted(self._phases.items())]
            walls = [{"wall": k, "count": int(v[0]),
                      "total_s": round(v[1], 6), "max_s": round(v[2], 6)}
                     for k, v in sorted(self._walls.items())]
            coverage = self._coverage_locked()
            sites = {site: {"compiles": st["compiles"], "hits": st["hits"],
                            "keys": sorted(str(k) for k in st["keys"]),
                            "budget": self._budgets.get(site),
                            "last_dur_s": round(st["last_dur_s"], 6)}
                     for site, st in sorted(self._sites.items())}
            warm = sum(st["compiles"] for st in self._sites.values())
            events = list(self._compile_events[-32:])
            storm = dict(self._storm) if self._storm else None
            divergence = [{"site": site, "key": str(key),
                           "flops": self._costs[(site, key)]["flops"],
                           "bytes": self._costs[(site, key)]["bytes"],
                           "samples": int(st.get("samples", 0)),
                           "achieved_bytes_per_s": round(
                               st.get("bps", 0.0), 3),
                           "achieved_flops_per_s": round(
                               st.get("fps", 0.0), 3),
                           "baseline_bytes_per_s": round(
                               st.get("baseline_bps", 0.0), 3),
                           "drift": round(st.get("drift", 1.0), 3)}
                          for (site, key), st in sorted(
                              self._div.items(), key=lambda kv: str(kv[0]))]
            slowest = sorted(self._recent, key=lambda r: -r[2])[:top_k]
            notes = list(self._notes)
        return {
            "phases": phases,
            "walls": walls,
            "coverage": round(coverage, 4),
            "compile_ledger": {"warm_set": warm, "sites": sites,
                               "events": events, "storm": storm},
            "divergence": divergence,
            "slowest": [{"phase": p, "dur_s": round(d, 6),
                         "trace_id": t, "ts_us": ts}
                        for ts, p, d, t in slowest],
            "notes": notes,
        }

    def alerts(self) -> Dict[str, Any]:
        """The /alerts contribution: cached storm + divergence state,
        served without touching the record path (read-only scrape)."""
        with self._lock:
            storm = dict(self._storm) if self._storm else None
            diverged = [{"site": site, "key": str(key),
                         "drift": round(st.get("drift", 1.0), 3)}
                        for (site, key), st in self._div.items()
                        if st.get("drift", 1.0) > self._div_factor]
        return {"installed": True, "compile_storm": storm,
                "divergence": diverged}


_INSTALL_LOCK = threading.Lock()


def install_profiler(registry: Registry,
                     clock: Callable[[], float] = time.perf_counter,
                     divergence_factor: float = DEFAULT_DIVERGENCE_FACTOR,
                     ):
    """Attach a Profiler to `registry` (first install wins, like
    install_slo_engine); returns the installed profiler.  A disabled
    registry gets the shared NULL_PROFILER."""
    if registry is None or not registry.enabled:
        return NULL_PROFILER
    prof = getattr(registry, "profile", None)
    if prof is None:
        with _INSTALL_LOCK:
            prof = getattr(registry, "profile", None)
            if prof is None:
                prof = Profiler(registry, clock=clock,
                                divergence_factor=divergence_factor)
                registry.profile = prof
    return prof


def profiler_for(registry: Optional[Registry]):
    """The registry's profiler (installing one with the default clock
    on first use), or NULL_PROFILER for a dark/absent registry."""
    if registry is None or not registry.enabled:
        return NULL_PROFILER
    prof = getattr(registry, "profile", None)
    if prof is not None:
        return prof
    return install_profiler(registry)


def compiled_call(registry: Optional[Registry], site: str, fn: Callable,
                  *args: Any, key: Any = "", phase: Optional[str] = None,
                  **kw: Any) -> Any:
    """Run a jitted callable with compile-ledger accounting: the ONE
    replacement for the hand-rolled ``fn._cache_size()`` diff blocks
    the decode paths used to carry (decode/beam_search.py,
    decode/speculative.py, decode/decoder.py).  Cache growth across the
    call = a fresh trace/compile; hit/miss lands in the established
    ``decode/compile_cache_*_total`` counters AND the compile ledger,
    and `phase` (when given) books the measured wall into the phase
    ledger too — one timing, both ledgers."""
    try:  # private jax API; telemetry must never break the dispatch
        before = fn._cache_size()
    except Exception:  # tslint: disable=TS005 — _cache_size is a private jax API; absent on some builds
        before = None
    prof = profiler_for(registry)
    t0 = prof.start()
    out = fn(*args, **kw)
    dt = prof.end(phase, t0) if phase is not None else (prof.start() - t0)
    if before is not None:
        try:
            missed = fn._cache_size() > before
            if registry is not None:
                registry.counter(
                    "decode/compile_cache_misses_total" if missed
                    else "decode/compile_cache_hits_total").inc()
            if missed:
                prof.record_compile(site, key, dt)
            else:
                prof.record_hit(site)
        except Exception:  # tslint: disable=TS005 — best-effort cache telemetry; the result is already in hand
            pass
    return out


def profile_payload(registry: Optional[Registry]) -> Dict[str, Any]:
    """The /profile endpoint body.  Quiet {installed: False} when no
    profiler has recorded on this registry."""
    prof = getattr(registry, "profile", None) if registry is not None \
        else None
    if prof is None or prof is NULL_PROFILER:
        return {"installed": False, "phases": [], "walls": [],
                "coverage": 0.0,
                "compile_ledger": {"warm_set": 0, "sites": {},
                                   "events": [], "storm": None},
                "divergence": [], "slowest": [], "notes": []}
    return {"installed": True, **prof.payload()}


def profile_alerts(registry: Optional[Registry]) -> Dict[str, Any]:
    """The profiler's /alerts contribution (merged by obs/http.py under
    the "profile" key).  Read-only; quiet when not installed."""
    prof = getattr(registry, "profile", None) if registry is not None \
        else None
    if prof is None or prof is NULL_PROFILER:
        return {"installed": False, "compile_storm": None,
                "divergence": []}
    return prof.alerts()
