"""Per-tenant / per-tier SLO burn-rate engine (ISSUE 15 tentpole,
piece 2).

PR 14 made the serving plane multi-tenant and PR 13 made it
multi-replica, but "are we meeting our latency promise to tenant X"
still required a human staring at histograms.  This module commits the
promise: declarative OBJECTIVES (``SLO_POLICY.json`` at the repo root —
a latency threshold classifying each request good/bad, or an error-rate
signal, grouped per tenant or per tier), evaluated over FAST and SLOW
sliding windows on an injectable clock, in the multi-window burn-rate
shape the SRE workbook standardised:

    burn rate = (bad fraction over the window) / (1 - target)

A burn rate of 1.0 spends the error budget exactly at the rate the
target allows; the committed thresholds page long before the budget is
gone.  Alert state per series (``ok | warn | page``) takes the MIN of
the two windows' burn rates — the fast window makes paging prompt, the
slow window keeps a brief blip from paging, and recovery is symmetric
(the fast window going clean clears the page).  Transitions INTO
``page`` fire an ``slo_burn`` flight-recorder dump (obs/flightrec.py),
so the ring of serve ticks strictly preceding the breach survives for
the post-mortem, exactly like ``train_nan``.

Wiring: ``install_slo_engine(registry, clock=...)`` attaches one engine
per registry (first install wins, like the EventSink); the serving
layer feeds it from the request lifecycle — ``ServingServer.submit``
and ``FleetRouter.submit`` attach a done-callback recording (tenant,
tier, latency, error) on each future's exactly-once resolution — and
evaluates it once per dispatch round.  ``/alerts`` (obs/http.py) serves
``alerts_payload``.  Virtual time: clock injection means the committed
gate (tests/test_slo_burn.py) drives breach and recovery as exact
scheduling facts, no sleeps.

Telemetry (labeled children, OBSERVABILITY.md): ``slo/burn_rate_fast``
/ ``slo/burn_rate_slow`` / ``slo/alert_state`` gauges and
``slo/good_total`` / ``slo/bad_total`` counters per (objective, key);
series are LRU-bounded (``slo/series_evictions_total``) so hostile
tenant names cannot grow the engine.  Import-light: no jax/numpy.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from textsummarization_on_flink_tpu.obs import flightrec
from textsummarization_on_flink_tpu.obs.registry import Registry

log = logging.getLogger(__name__)

#: alert states, in escalation order (the alert_state gauge's encoding)
STATES = ("ok", "warn", "page")
_STATE_CODE = {s: i for i, s in enumerate(STATES)}

#: bound on live (objective, key) series: past this, the
#: least-recently-updated series is dropped (counted in
#: slo/series_evictions_total) — same hostile-tenant-name posture as
#: the registry's label LRU
MAX_SLO_SERIES = 512

#: policy path resolution: env override, else the committed repo-root
#: file two levels above this package
ENV_POLICY = "TS_SLO_POLICY"
DEFAULT_POLICY_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "SLO_POLICY.json")


def resolve_policy_path() -> str:
    return os.environ.get(ENV_POLICY, "").strip() or DEFAULT_POLICY_PATH


def load_policy(path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """The parsed SLO policy, or None when the file is absent/invalid
    (an unreadable policy must never crash a serving job — it logs and
    the engine simply stays uninstalled)."""
    p = path or resolve_policy_path()
    try:
        with open(p, encoding="utf-8") as f:
            policy = json.load(f)
    except OSError:
        return None
    except ValueError:
        log.warning("SLO policy %s is not valid JSON; burn-rate engine "
                    "stays off", p)
        return None
    if not isinstance(policy, dict) or "objectives" not in policy:
        log.warning("SLO policy %s has no objectives; burn-rate engine "
                    "stays off", p)
        return None
    return policy


class Objective:
    """One declarative objective row of SLO_POLICY.json."""

    __slots__ = ("name", "signal", "by", "target", "latency_threshold_s")

    def __init__(self, spec: Dict[str, Any]):
        self.name = str(spec["name"])
        self.signal = str(spec.get("signal", "latency"))
        if self.signal not in ("latency", "error"):
            raise ValueError(
                f"objective {self.name!r}: signal must be latency|error, "
                f"got {self.signal!r}")
        self.by = str(spec.get("by", "tenant"))
        if self.by not in ("tenant", "tier"):
            raise ValueError(
                f"objective {self.name!r}: by must be tenant|tier, got "
                f"{self.by!r}")
        self.target = float(spec.get("target", 0.99))
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"objective {self.name!r}: target must be in (0, 1), got "
                f"{self.target}")
        self.latency_threshold_s = float(
            spec.get("latency_threshold_ms", 0.0)) / 1000.0
        if self.signal == "latency" and self.latency_threshold_s <= 0:
            raise ValueError(
                f"objective {self.name!r}: latency signal needs "
                f"latency_threshold_ms > 0")

    def classify(self, latency_s: float, error: bool) -> bool:
        """True when the request was GOOD under this objective."""
        if error:
            return False
        if self.signal == "latency":
            return latency_s <= self.latency_threshold_s
        return True


class _Series:
    """One (objective, key) sliding-window series: good/bad counts in
    fixed-width time buckets keyed ``int(t / bucket_secs)``, pruned past
    the slow window.  Mutated only under the engine lock."""

    __slots__ = ("buckets", "state", "last_t")

    def __init__(self) -> None:
        self.buckets: Dict[int, List[int]] = {}
        self.state = "ok"
        self.last_t = 0.0

    def push(self, idx: int, good: bool, keep_from: int) -> None:
        cell = self.buckets.get(idx)
        if cell is None:
            cell = self.buckets[idx] = [0, 0]
            # prune opportunistically on the same write path: the
            # per-series map stays O(slow window / bucket_secs)
            for old in [i for i in self.buckets if i < keep_from]:
                del self.buckets[old]
        cell[0 if good else 1] += 1

    def frac_bad(self, from_idx: int) -> Tuple[float, int]:
        """(bad fraction, event count) over buckets >= from_idx."""
        good = bad = 0
        for idx, (g, b) in self.buckets.items():
            if idx >= from_idx:
                good += g
                bad += b
        total = good + bad
        return (bad / total if total else 0.0), total


class SloEngine:
    """The per-registry burn-rate evaluator.

    ``record`` is the hot-path side (one dict update per request
    resolution, under one lock — declared a TS002 hot function: it runs
    inside every future's resolve fan-out); ``evaluate`` is the scrape/
    tick side (burn gauges + alert transitions + the slo_burn trigger).
    """

    def __init__(self, policy: Dict[str, Any], registry: Registry,
                 clock: Callable[[], float] = time.monotonic):
        self._reg = registry
        self._clock = clock
        self.objectives = [Objective(o) for o in policy["objectives"]]
        windows = policy.get("windows", {})
        self.fast_secs = float(windows.get("fast_secs", 300.0))
        self.slow_secs = float(windows.get("slow_secs", 3600.0))
        if not 0 < self.fast_secs <= self.slow_secs:
            raise ValueError("need 0 < fast_secs <= slow_secs")
        self.bucket_secs = float(
            windows.get("bucket_secs", max(self.fast_secs / 12.0, 1e-9)))
        thresholds = policy.get("thresholds", {})
        self.warn = float(thresholds.get("warn", 2.0))
        self.page = float(thresholds.get("page", 10.0))
        self._lock = threading.Lock()
        self._series: "OrderedDict[Tuple[str, str], _Series]" = OrderedDict()
        self._last_rows: List[Dict[str, Any]] = []
        self._by_obj = {o.name: o for o in self.objectives}
        self._g_fast = registry.gauge("slo/burn_rate_fast")
        self._g_slow = registry.gauge("slo/burn_rate_slow")
        self._g_state = registry.gauge("slo/alert_state")
        self._c_good = registry.counter("slo/good_total")
        self._c_bad = registry.counter("slo/bad_total")
        self._c_evicted = registry.counter("slo/series_evictions_total")
        # the slo/* metrics' label surface must hold one child per live
        # engine series, or every evaluate() tick would LRU-thrash the
        # gauge children past the registry's default 128 cap and an
        # engine-side paging series could be absent from the scraped
        # exposition — widen these (and only these) to the engine bound
        for m in (self._g_fast, self._g_slow, self._g_state,
                  self._c_good, self._c_bad):
            if hasattr(m, "_max_label_sets"):  # null metrics have none
                m._max_label_sets = max(m._max_label_sets,
                                        MAX_SLO_SERIES)

    # -- hot path --
    def record(self, tenant: str, tier: str, latency_s: float,
               error: bool = False) -> None:
        """Classify one finished request under every objective and land
        it in the matching series' current window bucket."""
        now = self._clock()
        idx = int(now / self.bucket_secs)
        keep_from = idx - int(math.ceil(self.slow_secs / self.bucket_secs))
        evicted = 0
        with self._lock:
            for obj in self.objectives:
                key = (tenant or "default") if obj.by == "tenant" \
                    else (tier or "default")
                skey = (obj.name, key)
                series = self._series.get(skey)
                if series is None:
                    series = self._series[skey] = _Series()
                    while len(self._series) > MAX_SLO_SERIES:
                        (ev_obj, ev_key), _ = self._series.popitem(
                            last=False)
                        # retire the evicted series' GAUGE children with
                        # it: a frozen slo/alert_state stuck at `page`
                        # would render on every scrape forever with no
                        # engine row left to ever update it (the
                        # good/bad COUNTERS stay — a stale monotonic
                        # total is honest, a stale gauge lies)
                        for m in (self._g_fast, self._g_slow,
                                  self._g_state):
                            m.remove_labels(objective=ev_obj, key=ev_key)
                        evicted += 1
                else:
                    self._series.move_to_end(skey)
                good = obj.classify(latency_s, error)
                series.push(idx, good, keep_from)
                series.last_t = now
                (self._c_good if good else self._c_bad).labels(
                    objective=obj.name, key=key).inc()
        if evicted:
            self._c_evicted.inc(evicted)

    # -- scrape/tick side --
    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Recompute every series' burn rates and alert state; returns
        the /alerts objective rows.  A transition INTO page dumps the
        flight-recorder ring (reason ``slo_burn``) — the frames strictly
        preceding the breach."""
        t = self._clock() if now is None else now
        idx = int(t / self.bucket_secs)
        fast_from = idx - int(math.ceil(self.fast_secs / self.bucket_secs)) + 1
        slow_from = idx - int(math.ceil(self.slow_secs / self.bucket_secs)) + 1
        rows: List[Dict[str, Any]] = []
        paged: List[Tuple[str, str, float]] = []
        with self._lock:
            for (oname, key), series in self._series.items():
                obj = self._by_obj.get(oname)
                if obj is None:  # objective removed by a policy reload
                    continue
                budget = max(1.0 - obj.target, 1e-9)
                frac_fast, n_fast = series.frac_bad(fast_from)
                frac_slow, n_slow = series.frac_bad(slow_from)
                burn_fast = frac_fast / budget
                burn_slow = frac_slow / budget
                # multi-window rule: both windows must burn for an
                # alert (fast alone = a blip; slow alone = an old
                # breach the fast window already proved is over)
                effective = min(burn_fast, burn_slow)
                state = ("page" if effective >= self.page
                         else "warn" if effective >= self.warn else "ok")
                if state == "page" and series.state != "page":
                    paged.append((oname, key, burn_fast))
                series.state = state
                rows.append({
                    "objective": oname, "by": obj.by, "key": key,
                    "signal": obj.signal, "target": obj.target,
                    "burn_fast": round(burn_fast, 4),
                    "burn_slow": round(burn_slow, 4),
                    "events_fast": n_fast, "events_slow": n_slow,
                    "state": state,
                })
            self._last_rows = rows
            # gauge writes stay UNDER the engine lock: a concurrent
            # record() evicting a series also removes its gauge
            # children, and an unlocked write here could resurrect one
            # AFTER that removal — a frozen slo/alert_state child no
            # engine row would ever update again (metric locks never
            # take the engine lock, so the nesting is deadlock-free and
            # is already record()'s own pattern)
            for row in rows:
                labels = {"objective": row["objective"], "key": row["key"]}
                self._g_fast.labels(**labels).set(row["burn_fast"])
                self._g_slow.labels(**labels).set(row["burn_slow"])
                self._g_state.labels(**labels).set(
                    _STATE_CODE[row["state"]])
        for oname, key, burn in paged:
            # the dump lands BEFORE anything else reacts: the ring holds
            # exactly the frames recorded up to the breach evaluation
            flightrec.trigger(self._reg, "slo_burn", objective=oname,
                              key=key, burn_fast=round(burn, 4))
            log.warning("SLO burn PAGE: objective %s key %s fast-window "
                        "burn %.2f", oname, key, burn)
        return rows

    def states(self) -> Dict[Tuple[str, str], str]:
        """{(objective, key): alert state} as of the last evaluate."""
        with self._lock:
            return {k: s.state for k, s in self._series.items()}

    def last_rows(self) -> List[Dict[str, Any]]:
        """The /alerts objective rows computed by the LAST ``evaluate``
        tick (empty before the first).  Read-only: scraping /alerts
        must never consume an alert transition or pay the slo_burn
        flight-dump I/O on the HTTP handler thread — transitions belong
        to the dispatch/router tick that evaluates once per round."""
        return self._last_rows


_install_lock = threading.Lock()


def install_slo_engine(registry: Registry,
                       clock: Callable[[], float] = time.monotonic,
                       policy: Optional[Dict[str, Any]] = None,
                       ) -> Optional[SloEngine]:
    """Attach an SloEngine to `registry` (first install wins, like the
    EventSink/flight recorder).  `policy` defaults to the committed
    SLO_POLICY.json (TS_SLO_POLICY overrides the path); returns None —
    and installs nothing — on a disabled registry or a missing policy.
    """
    if not registry.enabled:
        return None
    if registry.slo is None:
        pol = policy if policy is not None else load_policy()
        if pol is None:
            return None
        with _install_lock:
            if registry.slo is None:
                try:
                    registry.slo = SloEngine(pol, registry, clock=clock)
                except (KeyError, TypeError, ValueError):
                    log.exception("invalid SLO policy; burn-rate engine "
                                  "stays off")
                    return None
    return registry.slo


def record_request(registry: Registry, tenant: str, tier: str,
                   latency_s: float, error: bool = False) -> None:
    """Feed one finished request into `registry`'s engine; no-op when
    none is installed (the unarmed fast path is one attribute test)."""
    eng = registry.slo
    if eng is not None:
        eng.record(tenant, tier, latency_s, error=error)


def evaluate(registry: Registry) -> None:
    """Tick-side refresh of `registry`'s burn gauges/alert states;
    no-op when no engine is installed."""
    eng = registry.slo
    if eng is not None:
        eng.evaluate()


def alerts_payload(registry: Registry) -> Dict[str, Any]:
    """The /alerts JSON body: overall status (the worst series state)
    plus per-series rows; an engineless registry reports a quiet ok.
    READ-ONLY (the module's all-GET contract): serves the rows cached
    by the last tick-side ``evaluate`` — a scrape never mutates alert
    state or fires the slo_burn dump from the HTTP handler thread."""
    eng = registry.slo
    if eng is None:
        return {"status": "ok", "installed": False, "objectives": []}
    rows = eng.last_rows()
    worst = max((r["state"] for r in rows), key=lambda s: _STATE_CODE[s],
                default="ok")
    return {
        "status": worst,
        "installed": True,
        "windows": {"fast_secs": eng.fast_secs, "slow_secs": eng.slow_secs},
        "thresholds": {"warn": eng.warn, "page": eng.page},
        "objectives": rows,
    }


__all__ = ["SloEngine", "Objective", "install_slo_engine",
           "record_request", "evaluate", "alerts_payload", "load_policy",
           "resolve_policy_path"]
