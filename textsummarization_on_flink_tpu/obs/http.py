"""Live telemetry exposition plane (ISSUE 9 tentpole, piece 2).

``obs.render_text()`` has promised a Prometheus scrape surface since
ISSUE 1 ("text exposition is Prometheus-style so a scrape endpoint can
be bolted on without touching call sites"); this module bolts it on.
One stdlib ``ThreadingHTTPServer`` — no new dependencies — bound to
**localhost only** (the plane exposes internal state; anything wider is
a reverse proxy's job), OFF by default and enabled per process via
``TS_OBS_HTTP=<port>`` or per job via ``HParams(obs_http_port=...)``.

Endpoints (all GET, all read-only):

  * ``/metrics``  — ``registry.render_text()`` verbatim (text/plain):
    what Prometheus scrapes is byte-identical to what the in-process
    exposition renders, asserted by test;
  * ``/healthz``  — component liveness: heartbeats registered by the
    trainer loop / serve dispatch thread / EventSink flusher
    (``obs.heartbeat(name, period)``) plus every circuit breaker's
    state; any STALE HEARTBEAT flips the JSON status to "degraded" and
    the HTTP status to 503 (load balancers understand).  Breaker states
    are reported but informational — see health() for why 503-ing an
    open admission breaker would pin it open;
  * ``/snapshot`` — ``registry.snapshot(compact=True)`` as JSON, plus
    the registry's ``health_info`` facts under a ``health_info`` key
    (ISSUE 15 satellite: one scrape carries metrics + health context);
  * ``/spans``    — the newest buffered spans as unified event records
    (``?n=<count>``, default 200);
  * ``/alerts``   — the SLO burn-rate engine's per-objective states as
    of the last dispatch-tick evaluation (obs/slo.py ``alerts_payload``
    — read-only like every route here; a quiet ok when none is
    installed);
  * ``/exemplars`` — every histogram's stamped per-bucket trace
    exemplars as JSON (the ``--request <trace_id>`` jump-off point);
  * ``/fleet/metrics`` + ``/fleet/snapshot`` — the merged fleet view
    over the registries ``registry.fleet_sources`` names (wired by the
    FleetRouter; 404 with a hint on a fleetless registry): counters
    summed, gauges ``replica=``-labeled, histograms bucket-merged
    (obs/registry.py ``render_fleet_text`` / ``merge_fleet_snapshot``).

Staleness is computed from each component's own declared period (stale
= age > STALE_FACTOR * period) on the injectable monotonic clock, so
tests flip /healthz without sleeping.
"""

from __future__ import annotations

import http.server
import json
import logging
import os
import threading
import time
import urllib.parse
from typing import Any, Callable, Dict, Optional, Tuple

from textsummarization_on_flink_tpu.obs import profile as profile_lib
from textsummarization_on_flink_tpu.obs import slo as slo_lib
from textsummarization_on_flink_tpu.obs import spans as spans_lib
from textsummarization_on_flink_tpu.obs.registry import (
    Registry,
    _series_key,
    merge_fleet_snapshot,
    render_fleet_text,
)

log = logging.getLogger(__name__)

#: a heartbeat is stale once its age exceeds this many of its own
#: declared periods (3x tolerates one missed beat plus scheduling slop
#: without masking a genuinely wedged component)
STALE_FACTOR = 3.0

#: the declared period for the train/serve LOOP heartbeats (one beat
#: per iteration): deliberately generous — a single iteration
#: legitimately blocks for a first-call jit compile, a checkpoint
#: save, or the windowed metrics D2H, and none of those may 503 a
#: healthy process; steady-state wedges still surface within
#: STALE_FACTOR * this (~6 minutes).  ONE constant so the two loops'
#: /healthz semantics can never drift.
LOOP_HEARTBEAT_PERIOD = 120.0

_BREAKER_STATES = {0: "closed", 1: "half_open", 2: "open"}

#: wall-clock start of THIS process, captured at import: with the pid
#: it forms the /healthz incarnation identity (ISSUE 17) — a replica
#: supervisor distinguishes a restarted child (new pid/start_time) from
#: a wedged old one answering on a stale port
_PROCESS_START_TIME = time.time()


class HeartbeatBoard:
    """Component liveness: name -> (last beat, declared period).

    ``beat()`` is the hot-path side (one dict store under a lock per
    loop iteration); ``status()`` is the scrape side.  The clock is
    injectable so staleness tests never sleep.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._beats: Dict[str, Tuple[float, float]] = {}

    def beat(self, name: str, period: float = 10.0) -> None:
        with self._lock:
            self._beats[name] = (self._clock(), float(period))

    def retire(self, name: str) -> None:
        """Deregister a component that legitimately finished (a trainer
        that completed, a server that stopped): its silence is not a
        failure and must not hold /healthz at 503 for the rest of the
        process."""
        with self._lock:
            self._beats.pop(name, None)

    def status(self, stale_factor: float = STALE_FACTOR,
               ) -> Dict[str, Dict[str, Any]]:
        """{name: {age_seconds, period_seconds, ok}} — ok=False once the
        age exceeds stale_factor x the component's own period."""
        now = self._clock()
        with self._lock:
            beats = dict(self._beats)
        return {
            name: {
                "age_seconds": round(now - last, 3),
                "period_seconds": period,
                "ok": (now - last) <= stale_factor * period,
            }
            for name, (last, period) in sorted(beats.items())
        }


_board_init_lock = threading.Lock()


def board_for(registry: Registry) -> HeartbeatBoard:
    """The registry's heartbeat board, created on first use (same
    double-checked pattern as spans.tracer_for)."""
    b = registry.heartbeats
    if b is None:
        with _board_init_lock:
            b = registry.heartbeats
            if b is None:
                b = HeartbeatBoard()
                registry.heartbeats = b
    return b


def heartbeat(registry: Registry, name: str, period: float = 10.0) -> None:
    """Record one liveness beat for `name` (no-op when disabled)."""
    if not registry.enabled:
        return
    board_for(registry).beat(name, period=period)


def retire_heartbeat(registry: Registry, name: str) -> None:
    """Deregister `name` from `registry`'s board (component finished
    cleanly); no-op when disabled or never registered."""
    if not registry.enabled or registry.heartbeats is None:
        return
    registry.heartbeats.retire(name)


def set_health_info(registry: Registry, **info: Any) -> None:
    """Publish non-numeric health facts (e.g. the serving layer's
    effective ``serve_mode``) into `registry`'s /healthz payload.
    No-op when the registry is disabled."""
    if not registry.enabled:
        return
    current = getattr(registry, "health_info", None)
    if current is None:
        registry.health_info = dict(info)
    else:
        current.update(info)


#: ceiling on retained incidents per registry: incidents are rare,
#: page-worthy state transitions (a crash-looping replica), not an
#: event stream — a bounded deque-style list keeps /alerts small
_MAX_INCIDENTS = 64


def add_incident(registry: Registry, kind: str, **fields: Any) -> None:
    """File one page-worthy incident (e.g. ``replica_crashloop``) onto
    `registry`'s /alerts payload (ISSUE 17).  Incidents are the
    non-SLO alert channel: the burn-rate engine prices request
    outcomes, while an incident records a STATE the operator must act
    on (a replica held out of rotation).  No-op when disabled."""
    if not registry.enabled:
        return
    row = {"kind": kind, **fields}
    current = getattr(registry, "incidents", None)
    if current is None:
        registry.incidents = [row]
    else:
        current.append(row)
        del current[:-_MAX_INCIDENTS]


def incidents(registry: Registry) -> list:
    """The registry's filed incidents, newest last ([] when none)."""
    return list(getattr(registry, "incidents", None) or ())


#: gauges the /healthz body surfaces as routing inputs (ISSUE 13: the
#: FleetRouter's least-loaded pick reads queue depth and free slots off
#: each replica's health plane — they must be scrapeable, not in-process
#: only).  Reported only when the gauge exists on the registry.
_SERVE_HEALTH_GAUGES = (
    ("queue_depth", "serve/queue_depth"),
    ("slots_free", "serve/slots_free"),
)


def health(registry: Registry,
           stale_factor: float = STALE_FACTOR) -> Dict[str, Any]:
    """The /healthz payload: heartbeat statuses + breaker states.

    Only a STALE HEARTBEAT degrades (ISSUE 9: "stale-heartbeat ->
    degraded").  Breaker states are reported but informational, for two
    reasons: the ``*/breaker_state`` gauge only refreshes on the next
    ``allow()`` call, so an OPEN reading may already be past its reset
    window; and 503-ing on an open ADMISSION breaker is a
    self-sustaining trap — the load balancer drains the instance, no
    traffic arrives, no half-open probe ever runs, and the breaker can
    never close again.  Scrapers that want to alert on breakers read
    the ``breakers`` map (or /metrics) directly."""
    components = (board_for(registry).status(stale_factor)
                  if registry.enabled else {})
    breakers: Dict[str, str] = {}
    for name in registry.names():
        if not name.endswith("/breaker_state"):
            continue
        metric = registry.get(name)
        code = int(getattr(metric, "value", 0))
        # resilience/<name>/breaker_state -> <name>
        short = name[len("resilience/"):-len("/breaker_state")] \
            if name.startswith("resilience/") else name
        breakers[short] = _BREAKER_STATES.get(code, str(code))
    # serving routing inputs (ISSUE 13): queue depth / free slots off
    # the existing gauges plus any published facts (effective
    # serve_mode).  Informational like the breakers — they never flip
    # the 503; the FleetRouter (and any external LB) reads them to pick
    # the least-loaded replica without a second endpoint.
    serve: Dict[str, Any] = {}
    names = set(registry.names())
    for key, metric in _SERVE_HEALTH_GAUGES:
        if metric in names:
            serve[key] = getattr(registry.get(metric), "value", 0)
    info = getattr(registry, "health_info", None)
    if info:
        serve.update(info)
    degraded = any(not c["ok"] for c in components.values())
    payload: Dict[str, Any] = {
        "status": "degraded" if degraded else "ok",
        "components": components,
        "breakers": breakers,
        # incarnation identity (ISSUE 17): pid + process start time +
        # stamped replica id let a process supervisor verify WHICH
        # incarnation answered — a stale portfile pointing at a
        # previous (or foreign) pid must not pass readiness
        "pid": os.getpid(),
        "start_time": _PROCESS_START_TIME,
        "replica_id": getattr(registry, "replica_id", "") or "",
    }
    if serve:
        payload["serve"] = serve
    return payload


def exemplars(registry: Registry) -> list:
    """The /exemplars payload: every histogram series' stamped
    per-bucket trace exemplars — [{metric, le, trace_id, value}], the
    machine-readable side of the OpenMetrics ``# {trace_id=...}``
    annotations /metrics renders (ISSUE 15: ``scripts/trace_summary.py
    --request <trace_id>`` turns any row into a full request
    timeline)."""
    return [{"metric": _series_key(name, labels_kv), **ex}
            for name, labels_kv, kind, payload in registry.series()
            if kind == "histogram"
            for ex in payload["exemplars"]]


class _Handler(http.server.BaseHTTPRequestHandler):
    """Routes the four endpoints over the registry the server wraps."""

    server_version = "ts-obs/1"
    registry: Registry = None  # type: ignore[assignment] # set per server

    # -- plumbing --
    def log_message(self, fmt: str, *args: Any) -> None:
        log.debug("obs-http %s", fmt % args)  # never spam stderr

    def _send(self, code: int, body: bytes,
              content_type: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-write; nothing to recover

    def _send_json(self, code: int, payload: Any) -> None:
        self._send(code, (json.dumps(payload) + "\n").encode("utf-8"))

    # -- routes --
    def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        parsed = urllib.parse.urlparse(self.path)
        route = parsed.path.rstrip("/") or "/"
        reg = self.registry
        try:
            if route == "/metrics":
                # exemplar annotations are OPENMETRICS syntax — a
                # Prometheus text-format (0.0.4) parser rejects the
                # trailing `# {...}` as an invalid timestamp and fails
                # the whole scrape, so the annotated body is served
                # only to scrapers whose Accept header negotiates it
                # (/exemplars carries the same data as JSON regardless)
                openmetrics = "openmetrics" in (
                    self.headers.get("Accept") or "")
                self._send(
                    200,
                    reg.render_text(exemplars=openmetrics,
                                    openmetrics=openmetrics).encode("utf-8"),
                    content_type=(
                        "application/openmetrics-text; version=1.0.0; "
                        "charset=utf-8" if openmetrics
                        else "text/plain; version=0.0.4"))
            elif route == "/healthz":
                payload = health(reg)
                self._send_json(200 if payload["status"] == "ok" else 503,
                                payload)
            elif route == "/snapshot":
                snap: Dict[str, Any] = reg.snapshot(compact=True)
                # ISSUE 15 satellite: the PR-13 routing inputs
                # (serve_mode, params_fingerprint, replica, ...) ride
                # the snapshot so one scrape carries metrics + health
                # context together
                info = getattr(reg, "health_info", None)
                if info:
                    snap["health_info"] = dict(info)
                self._send_json(200, snap)
            elif route == "/spans":
                qs = urllib.parse.parse_qs(parsed.query)
                try:
                    n = max(1, int(qs.get("n", ["200"])[0]))
                except ValueError:
                    n = 200
                recs = spans_lib.tracer_for(reg).finished() if reg.enabled \
                    else []
                self._send_json(200, [r.as_event() for r in recs[-n:]])
            elif route == "/alerts":
                payload = slo_lib.alerts_payload(reg)
                # the profiler's cached storm/divergence state rides the
                # same scrape (ISSUE 16) — read-only, like the SLO rows
                payload["profile"] = profile_lib.profile_alerts(reg)
                # filed incidents (ISSUE 17): non-SLO page-worthy
                # states — a crash-looping replica held out of rotation
                payload["incidents"] = incidents(reg)
                self._send_json(200, payload)
            elif route == "/profile":
                # performance attribution plane (obs/profile.py, ISSUE
                # 16): phase table, compile ledger, divergence table,
                # top-k slowest dispatches.  Served from state cached on
                # the record side — a scrape never mutates the ledgers.
                self._send_json(200, profile_lib.profile_payload(reg))
            elif route == "/exemplars":
                self._send_json(200, exemplars(reg))
            elif route in ("/fleet/metrics", "/fleet/snapshot"):
                sources = getattr(reg, "fleet_sources", None)
                if sources is None:
                    self._send_json(404, {
                        "error": "no fleet behind this registry (the "
                                 "FleetRouter wires registry."
                                 "fleet_sources)"})
                elif route == "/fleet/metrics":
                    self._send(200,
                               render_fleet_text(sources()).encode("utf-8"),
                               content_type="text/plain; version=0.0.4")
                else:
                    self._send_json(200, merge_fleet_snapshot(sources()))
            else:
                self._send_json(404, {"error": f"no route {route!r}",
                                      "routes": ["/metrics", "/healthz",
                                                 "/snapshot", "/spans",
                                                 "/alerts", "/profile",
                                                 "/exemplars",
                                                 "/fleet/metrics",
                                                 "/fleet/snapshot"]})
        except Exception:  # tslint: disable=TS005 — exposition must never kill the scrape thread; failures are counted and answered with a 500
            reg.counter("obs/http_errors_total").inc()
            log.exception("obs-http handler failed for %s", self.path)
            try:
                self._send_json(500, {"error": "internal"})
            except Exception:  # tslint: disable=TS005 — socket already gone; the error counter above recorded the failure
                pass


class ObsHttpServer:
    """The exposition plane over one registry: localhost-only
    ThreadingHTTPServer on a daemon thread.

        srv = ObsHttpServer(registry, port=9464).start()
        ... GET http://127.0.0.1:{srv.port}/metrics ...
        srv.close()

    ``port=0`` binds an OS-assigned ephemeral port (tests); the bound
    port is always on ``.port``.
    """

    def __init__(self, registry: Registry, port: int = 0,
                 host: str = "127.0.0.1"):
        handler = type("_BoundHandler", (_Handler,), {"registry": registry})
        self._httpd = http.server.ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        self._registry = registry

    def start(self) -> "ObsHttpServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name="obs-http")
            self._thread.start()
            log.info("obs exposition plane listening on http://%s:%d "
                     "(/metrics /healthz /snapshot /spans)",
                     self.host, self.port)
        return self

    def close(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
            self._thread = None
        self._httpd.server_close()

    def __enter__(self) -> "ObsHttpServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


_default_server: Optional[ObsHttpServer] = None
_default_server_lock = threading.Lock()


def resolve_http_port(hps: Any = None) -> int:
    """The exposition port for this job: ``HParams.obs_http_port`` when
    set (> 0), else ``TS_OBS_HTTP=<port>``, else 0 (off)."""
    if hps is not None and getattr(hps, "obs_http_port", 0):
        return int(hps.obs_http_port)
    raw = os.environ.get("TS_OBS_HTTP", "").strip()
    if not raw:
        return 0
    try:
        port = int(raw)
    except ValueError:
        port = -1
    if not 0 < port <= 65535:
        # the env contract is log-and-stay-off, NEVER crash the job: an
        # out-of-range port would raise OverflowError at bind, past
        # maybe_serve's OSError net, killing Trainer/ServingServer init
        log.warning("TS_OBS_HTTP=%r is not a valid port (1-65535); "
                    "exposition plane stays off", raw)
        return 0
    return port


def maybe_serve(registry: Registry, hps: Any = None,
                ) -> Optional[ObsHttpServer]:
    """Start the process-wide exposition plane when configured (one
    server per process — the first enabler wins; later calls return the
    running instance).  None when off (the default) or disabled."""
    global _default_server
    if not registry.enabled:
        return None
    port = resolve_http_port(hps)
    if port <= 0:
        return None
    with _default_server_lock:
        if _default_server is None:
            try:
                _default_server = ObsHttpServer(registry, port=port).start()
            except OSError as e:
                log.warning("obs exposition plane failed to bind port %d: "
                            "%s", port, e)
                return None
        return _default_server
