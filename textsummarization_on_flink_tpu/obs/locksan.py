"""Runtime lock-order sanitizer (``TS_LOCKSAN=1``).

The static side of the story lives in tools/tslint (TS007 derives the
lock acquisition-order graph from the call graph); this is the dynamic
side: an opt-in instrumented lock that records the REAL per-thread
acquisition order, fails fast on an inversion, and can cross-check what
actually ran against what the analyzer predicted.

Usage — replace direct ``threading.Lock()`` construction with the
factories, naming each lock the way tslint names it (``Class.attr``)::

    from textsummarization_on_flink_tpu.obs import locksan
    self._lock = locksan.make_lock("RemoteReplica._lock")

With ``TS_LOCKSAN`` unset the factories return PLAIN ``threading``
primitives — zero wrapper, zero overhead, nothing to reason about in
production.  With ``TS_LOCKSAN=1`` every acquisition:

* pushes onto a per-thread held-lock stack and increments
  ``obs/locksan_acquisitions_total``;
* adds ``held -> acquiring`` edges to a process-global order graph;
* **fails fast** if the opposite edge was ever observed: the acquire is
  rolled back (the inner lock is released), a
  ``lock_inversion`` flight dump is written via obs/flightrec, and the
  typed :class:`LockOrderInversionError` is raised — a deadlock that
  would have been a wedged process under unlucky scheduling becomes a
  loud test failure under ANY scheduling that exercises both orders;
* optionally cross-checks each NEW edge against the statically derived
  graph (``TS_LOCKSAN_GRAPH=path`` to the JSON written by
  ``python -m tools.tslint --lock-graph``): an edge the analyzer never
  predicted counts ``obs/locksan_unmodeled_edges_total`` — the witness
  that the static model and real execution have drifted apart.

Kill conditions (when to turn it OFF): locksan is a test/chaos-rig
tool.  The wrapper adds a dict/stack bookkeeping cost per acquisition
and one process-global mutex on the order graph — never enable it on a
latency-measuring run, and never ship metrics from a sanitized run to
a perf baseline.  Reentrant acquisition of the same sanitized lock
(RLock) records no self-edges.

Caveat: do not hand a sanitized **RLock** to ``threading.Condition`` —
the Condition would probe ownership through ``acquire(False)``, which
succeeds reentrantly and corrupts its bookkeeping.  Use
:func:`make_condition` (plain-Lock based) for condition variables.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Set

__all__ = [
    "LockOrderInversionError", "make_lock", "make_rlock", "make_condition",
    "active", "configure", "snapshot", "reset",
]

_TRUTHY = ("1", "true", "on", "yes")


class LockOrderInversionError(RuntimeError):
    """Two locks were acquired in opposite orders by different code
    paths — the classic AB/BA deadlock, caught at the second acquire."""

    def __init__(self, message: str, acquiring: str = "",
                 held: Optional[List[str]] = None,
                 flight_dump: Optional[str] = None):
        super().__init__(message)
        self.acquiring = acquiring
        self.held = list(held or ())
        self.flight_dump = flight_dump


def _env_enabled() -> bool:
    return os.environ.get("TS_LOCKSAN", "").strip().lower() in _TRUTHY


class _Sanitizer:
    """Process-global order graph + per-thread held stacks."""

    def __init__(self) -> None:
        self._mu = threading.Lock()  # plain on purpose: guards the graph
        #: observed order: edges[a] contains b iff b was acquired with a
        #: held (a "happened-before" b inside some thread)
        self.edges: Dict[str, Set[str]] = {}
        self.static_edges: Optional[Dict[str, Set[str]]] = None
        self.static_path: Optional[str] = None
        self._tls = threading.local()
        self.acquisitions = 0
        self.inversions = 0
        self.unmodeled = 0

    # -- per-thread stack --------------------------------------------------

    def _stack(self) -> List["SanitizedLock"]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    # -- static graph ------------------------------------------------------

    def load_static(self, path: str) -> None:
        with open(path, "r", encoding="utf-8") as f:
            payload = json.load(f)
        edges: Dict[str, Set[str]] = {}
        for a, b in payload.get("edges", ()):
            edges.setdefault(a, set()).add(b)
        # transitive closure: the analyzer reports direct edges; runtime
        # stacks witness ancestors too (A held while C acquired through B)
        changed = True
        while changed:
            changed = False
            for a in list(edges):
                reach = edges[a]
                for b in list(reach):
                    extra = edges.get(b, set()) - reach - {a}
                    if extra:
                        reach |= extra
                        changed = True
        self.static_edges = edges
        self.static_path = path

    # -- events ------------------------------------------------------------

    def on_acquired(self, lock: "SanitizedLock") -> None:
        stack = self._stack()
        reentrant = any(h is lock for h in stack)
        held = []
        if not reentrant:
            seen: Set[str] = set()
            for h in stack:
                if h.name != lock.name and h.name not in seen:
                    seen.add(h.name)
                    held.append(h.name)
        inversion_against: Optional[str] = None
        unmodeled = 0
        with self._mu:
            self.acquisitions += 1
            for h in held:
                if h in self.edges.get(lock.name, ()):
                    inversion_against = h
                    break
            if inversion_against is None:
                for h in held:
                    dst = self.edges.setdefault(h, set())
                    if lock.name not in dst:
                        dst.add(lock.name)
                        if (self.static_edges is not None
                                and lock.name
                                not in self.static_edges.get(h, ())):
                            unmodeled += 1
                self.unmodeled += unmodeled
            else:
                self.inversions += 1
        _emit(lambda o: o.counter("obs/locksan_acquisitions_total").inc(1))
        if inversion_against is not None:
            dump = _flight_dump(lock.name, inversion_against, held)
            _emit(lambda o: o.counter("obs/locksan_inversions_total").inc(1))
            raise LockOrderInversionError(
                f"lock-order inversion: acquiring {lock.name} while "
                f"holding {held} but {lock.name} -> {inversion_against} "
                f"was previously observed — AB/BA deadlock under "
                f"adversarial scheduling",
                acquiring=lock.name, held=held, flight_dump=dump)
        if unmodeled:
            _emit(lambda o: o.counter(
                "obs/locksan_unmodeled_edges_total").inc(unmodeled))
        stack.append(lock)

    def on_released(self, lock: "SanitizedLock") -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return


def _emit(inc: Any) -> None:
    """Mirror a sanitizer event into the default obs registry (call
    sites pass the literal metric name so the OBSERVABILITY.md
    doc-drift gate sees it)."""
    try:
        from textsummarization_on_flink_tpu import obs
        inc(obs)
    except Exception:  # tslint: disable=TS005 — the sanitizer must never take the process down through its own telemetry; the in-object counters in snapshot() stay exact
        pass


def _flight_dump(acquiring: str, prior: str,
                 held: List[str]) -> Optional[str]:
    try:
        from textsummarization_on_flink_tpu import obs
        from textsummarization_on_flink_tpu.obs import flightrec
        return flightrec.trigger(
            obs.registry(), "lock_inversion",
            acquiring=acquiring, held=held,
            prior_edge=f"{acquiring} -> {prior}",
            thread=threading.current_thread().name)
    except Exception:  # tslint: disable=TS005 — flight capture is best-effort evidence; the typed LockOrderInversionError below is the failure signal itself
        return None


class SanitizedLock:
    """Order-checking wrapper over a ``threading`` lock primitive.
    Context-manager and acquire/release compatible; ``Condition`` can
    wrap the plain-Lock flavor (it falls back to its default
    ``_is_owned`` probe, which this wrapper answers correctly)."""

    def __init__(self, name: str, inner: Any, san: _Sanitizer) -> None:
        self.name = name
        self._inner = inner
        self._san = san

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            return False
        try:
            self._san.on_acquired(self)
        except LockOrderInversionError:
            self._inner.release()  # roll back: fail the acquire, typed
            raise
        return True

    def release(self) -> None:
        self._san.on_released(self)
        self._inner.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return bool(locked()) if locked is not None else False

    def __enter__(self) -> "SanitizedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"SanitizedLock({self.name!r})"


_SAN = _Sanitizer()
_ACTIVE = _env_enabled()
if _ACTIVE and os.environ.get("TS_LOCKSAN_GRAPH"):
    try:
        _SAN.load_static(os.environ["TS_LOCKSAN_GRAPH"])
    except (OSError, ValueError):
        pass  # missing/broken graph: sanitize without the cross-check


def active() -> bool:
    """True when locks built by the factories are sanitized."""
    return _ACTIVE


def configure(enabled: Optional[bool] = None,
              static_graph: Optional[str] = None) -> None:
    """Re-latch the sanitizer (tests; production uses the env vars at
    import).  Locks created BEFORE enabling stay plain — construct the
    objects under test after calling this."""
    global _ACTIVE
    if enabled is not None:
        _ACTIVE = bool(enabled)
    if static_graph is not None:
        _SAN.load_static(static_graph)


def reset() -> None:
    """Drop the observed order graph and counters (test isolation)."""
    with _SAN._mu:
        _SAN.edges.clear()
        _SAN.acquisitions = 0
        _SAN.inversions = 0
        _SAN.unmodeled = 0


def snapshot() -> Dict[str, Any]:
    """Exact in-object view (the obs counters mirror these but share the
    default registry with everything else in the process)."""
    with _SAN._mu:
        return {
            "active": _ACTIVE,
            "acquisitions": _SAN.acquisitions,
            "inversions": _SAN.inversions,
            "unmodeled_edges": _SAN.unmodeled,
            "order_edges": sorted(
                (a, b) for a, bs in _SAN.edges.items() for b in bs),
            "static_graph": _SAN.static_path,
        }


def make_lock(name: str) -> Any:
    """A ``threading.Lock`` — sanitized when TS_LOCKSAN is on."""
    if not _ACTIVE:
        return threading.Lock()
    return SanitizedLock(name, threading.Lock(), _SAN)


def make_rlock(name: str) -> Any:
    """A ``threading.RLock`` — sanitized when TS_LOCKSAN is on
    (reentrant re-acquisition records no self-edges)."""
    if not _ACTIVE:
        return threading.RLock()
    return SanitizedLock(name, threading.RLock(), _SAN)


def make_condition(name: str, lock: Optional[Any] = None) -> Any:
    """A ``threading.Condition``.  Pass ``lock`` to share a mutex built
    by :func:`make_lock` (the wait/notify protocol releases and
    re-acquires THROUGH the sanitized wrapper, so condition waits stay
    visible to the order graph); default builds its own."""
    if lock is None:
        lock = make_lock(name)
    return threading.Condition(lock)
