"""Reduce-pass input assembly for hierarchical summarization (ISSUE 19).

The reduce pass of serve/hiersum.py decodes ONE more request whose
encoder input is the concatenation of the per-chunk summaries.  That
input must fit the decode-side encoder horizon (``max_enc_steps``) —
and HOW it is truncated is a quality decision, not a formatting one:
naive head-truncation of the concatenation silently deletes the tail
chunks from the document's summary, which is exactly the
missing-coverage failure the cross-chunk copy-fidelity metric exists to
catch.  So the budgeting rule here keeps every chunk represented:

  * when everything fits, the summaries concatenate verbatim in chunk
    order (document order is meaning-bearing for news-style text);
  * when over budget, each chunk summary keeps an equal word budget
    (``max_words // n_chunks``, min 1) from its FRONT — summary-leading
    words carry the most content for this model family — and chunk
    order is preserved.

Lives in decode/ because it shapes the encoder input of a decode pass
(the reduce request is a plain submit; the serving layer neither knows
nor cares that its article was assembled).  Import-light: no jax — the
serve layer imports this on its hot path.
"""

from __future__ import annotations

from typing import List, Sequence


def assemble_reduce_input(chunk_summaries: Sequence[Sequence[str]],
                          max_words: int) -> str:
    """Concatenate per-chunk summary words into the reduce pass's
    article, budgeted so every chunk survives truncation (see module
    docstring).  Empty chunk summaries are skipped; an all-empty map
    yields "" (the caller treats that as a failed document rather than
    decoding an empty article)."""
    if max_words < 1:
        raise ValueError(f"max_words must be >= 1, got {max_words}")
    parts: List[List[str]] = [list(s) for s in chunk_summaries if s]
    if not parts:
        return ""
    total = sum(len(p) for p in parts)
    if total > max_words:
        budget = max(1, max_words // len(parts))
        parts = [p[:budget] for p in parts]
    words: List[str] = []
    for p in parts:
        words.extend(p)
    # the equal-budget floor of 1 word/chunk can still overflow for
    # extreme fan-outs (n_chunks > max_words); the hard cap keeps the
    # contract absolute and drops trailing chunks LAST
    return " ".join(words[:max_words])


__all__ = ["assemble_reduce_input"]
