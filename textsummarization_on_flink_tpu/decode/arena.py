"""Host-side page arena for the paged resident state (ISSUE 20).

The continuous engine's resident HBM used to be provisioned per SLOT at
the worst-case article shape — PR 11's length masks cut compute, not
memory.  This module is the HOST half of the fix: a free-list allocator
over a fixed pool of ``decode_enc_block``-row pages.  The device half
(decode/beam_search.py's ``*_paged_jit`` kernels) holds the pooled
encoder-axis leaves; the engine (decode/decoder.SlotDecodeEngine) calls
``alloc`` at pack time with the admitted article's true page count and
``free`` at harvest/release, and mirrors the allocation into the
per-slot page-table rows it passes to the kernels as DATA (never shape
— the compile-once discipline of PRs 6/11).

Deliberately jax-free: allocation runs on the serving dispatch thread
between chunks (a tslint TS002 hot path) — pure numpy, no device sync.

``ArenaExhaustedError`` is the typed backpressure signal: the batcher
catches it and REQUEUES the admission (never a wrong decode, never a
dropped request) until a harvest frees pages.
"""

from __future__ import annotations

from typing import Iterable, List

import numpy as np

# the typed backpressure signal LIVES in resilience/errors.py (the
# repo's failure vocabulary, import-light) so the jax-free serve
# scheduler can catch it without importing the jax-heavy decode
# package; re-exported here because the arena is what raises it
from textsummarization_on_flink_tpu.resilience.errors import (  # noqa: F401
    ArenaExhaustedError,
)

__all__ = ["ArenaExhaustedError", "PageArena"]


class PageArena:
    """LIFO free-list over page ids ``0..pages-1``.

    LIFO on purpose: a just-freed page is the page most likely still
    warm in cache, and reuse churn is exactly what the allocation-
    pattern compile pin exercises.  The SCRATCH page (id ``pages`` by
    the kernels' convention) is NOT managed here — it is never
    allocated, never freed, and every unused page-table entry points at
    it."""

    def __init__(self, pages: int):
        if pages < 1:
            raise ValueError(f"arena needs at least one page, got {pages}")
        self._capacity = int(pages)
        self._free: List[int] = list(range(pages - 1, -1, -1))
        self._owned = np.zeros(pages, dtype=bool)

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self._capacity - len(self._free)

    @property
    def fill(self) -> float:
        """In-use fraction in [0, 1] — the serve/arena_fill observable."""
        return self.pages_in_use / self._capacity

    def alloc(self, n: int) -> np.ndarray:
        """Allocate ``n`` pages; returns their ids as int32 [n].  Raises
        typed ``ArenaExhaustedError`` (allocating NOTHING — admission is
        all-or-nothing, so a failed pack leaks no pages)."""
        n = int(n)
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            raise ArenaExhaustedError(
                f"arena exhausted: need {n} pages, {len(self._free)} free "
                f"of {self._capacity}", needed=n, free=len(self._free))
        ids = [self._free.pop() for _ in range(n)]
        self._owned[ids] = True
        return np.asarray(ids, dtype=np.int32)

    def free(self, ids: Iterable[int]) -> None:
        """Return pages to the free list.  Double-free and out-of-range
        ids raise — an accounting bug must fail loudly, not silently
        hand one page to two residents."""
        for pid in np.asarray(list(ids), dtype=np.int64).tolist():  # tslint: disable=TS002 — host numpy id normalization, no device value
            if not 0 <= pid < self._capacity:
                raise ValueError(
                    f"page id {pid} outside arena of {self._capacity}")
            if not self._owned[pid]:
                raise ValueError(f"double free of page {pid}")
            self._owned[pid] = False
            self._free.append(int(pid))  # tslint: disable=TS002 — plain python int from .tolist(), no device value
