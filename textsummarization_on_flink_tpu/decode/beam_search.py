"""On-device batched beam search.

Semantics parity with the reference's Python-side beam search
(/root/reference/src/main/python/pointer-generator/beam_search.py), but the
entire search runs inside one jitted on-device loop per dispatch — a
`lax.scan` over max_dec_steps with masked updates, or a `lax.while_loop`
with early exit, auto-selected per backend (TS_BEAM_LOOP, see
_loop_kind) — instead of ~100 `sess.run` round trips per article
(SURVEY.md §3.4):

  * at step 0 only the first (all-identical) hypothesis is expanded
    (beam_search.py:125 `num_orig_hyps`);
  * each live hypothesis proposes `2*beam_size` continuations
    (beam_search.py:127-141, model.py:280-285);
  * candidates are processed in descending score order: a STOP candidate
    moves to the results pool only if at least `min_dec_steps` tokens were
    generated (earlier STOPs are *discarded*), anything else refills the
    live beam, and processing halts once either pool holds `beam_size`
    entries (beam_search.py:143-154);
  * the loop ends when `beam_size` results exist or `max_dec_steps` is
    reached; an empty results pool falls back to the live beam
    (beam_search.py:158-162);
  * final ranking is by length-normalized total log-prob, where the length
    includes the START token like the reference's
    `len(self.tokens)` (beam_search.py:71-79,164-168).

Because live hypotheses all share the same length at any step, ordering by
total log-prob during the search equals the reference's ordering by average
log-prob; the average only matters for the final cross-length ranking.

TPU-first details: all shapes are static — the per-step candidate triage
is a pure cumulative-sum computation over the `beam*2*beam` sorted
candidates (no data-dependent Python), and a whole batch of B articles is
searched per dispatch via `vmap`.  OOV ids are mapped back to UNK before
the embedding lookup inside the loop (beam_search.py:112).

Byte diet (ISSUE 7; PERF.md "Decode byte diet"): the loop body never
materializes per-hypothesis trajectories.  Instead of gathering and
rewriting full `[K, T]` token and `[K, T, T_enc]` attention histories
through `x[parent]` every step (per-step traffic scaling with
`beam x T_dec x T_enc`), each step appends ONE column of backpointers —
parent slot, chosen token, and the step's raw attention/p_gen rows — at
`[:, t]`, and a finished hypothesis is recorded as four scalars
(log-prob, length, finish step, parent slot).  `_finalize_beam`
reconstructs the single winning trajectory with one reverse `lax.scan`
over the backpointer columns at the very end.  Token-exact with the
materialized-history search (pinned by the parity suite).

Model-family-agnostic: the search drives the (init_state, step) beam
adapter of ``hps.model_family`` (models/__init__.get_family), carrying the
model's decode state — LSTM cell + coverage, or a transformer KV cache —
as an opaque pytree whose leaves lead with the beam axis.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import START_ID, STOP_ID, UNK_ID
from textsummarization_on_flink_tpu.models import get_family

Array = jax.Array

NEG = -1e30  # effectively -inf, without inf-inf NaN hazards

_loop_kind_logged: Dict[str, bool] = {}


def _loop_kind(kind: Optional[str] = None) -> str:
    """Resolve the decode-loop construct: 'while' (early exit once every
    article's beam finishes), 'scan' (fixed max_dec_steps trip count),
    or 'chunked' (while over TS_BEAM_CHUNK-step scan chunks — early exit
    at chunk granularity with only ceil(T/C) dynamic iterations).

    All three produce IDENTICAL results: under vmap a while_loop already
    applies masked per-article updates until the slowest article's cond
    goes false; scan merely fixes the trip count at the worst case, and
    chunked interleaves the two at chunk granularity.  What
    scan buys is freedom from per-iteration host involvement — on an
    RPC-proxied backend (the tunneled axon TPU) every dynamic-condition
    loop iteration costs ~1.4 ms of round trip, ~140 ms per batch at the
    reference's max_dec_steps=100, while a scan dispatches once.  On a
    directly attached backend while's condition evaluates on device, so
    its early exit is free and saves the tail steps.

    TS_BEAM_LOOP=while|scan|chunked|auto; auto (the default) picks scan
    when the backend is the RPC-proxied axon plugin, else chunked —
    promoted into the auto ladder (ISSUE 7 satellite) now that the
    tail-chunk parity suite (test_beam_search: chunk 1/3/5/13, the
    no-early-exit regime, and the slot kernels) pins it token-exact:
    on a direct-attached backend chunked keeps while's early exit at
    chunk granularity while paying only ceil(T/C) dynamic iterations.
    `while` stays available as the explicit fallback (TS_BEAM_LOOP=while)
    and remains the degenerate safety default should backend probing
    fail mid-init.  The resolved kind is logged once so a mis-detection
    is visible in decode logs (ADVICE r2: JAX_PLATFORMS alone misses
    plugin auto-registration).
    """
    kind = (kind or os.environ.get("TS_BEAM_LOOP", "auto")).lower()
    if kind == "auto":
        proxied = "axon" in os.environ.get("JAX_PLATFORMS", "").lower()
        if not proxied:
            # the plugin may have been picked up via auto-registration or
            # JAX_PLATFORM_NAME rather than JAX_PLATFORMS; ask jax which
            # backend actually resolved (cheap after first init)
            try:
                proxied = "axon" in jax.default_backend().lower()
            except Exception:  # tslint: disable=TS005 — ANY backend-init failure must fall through to the conservative 'while' default, never break decode
                if not _loop_kind_logged.get("while"):
                    _loop_kind_logged["while"] = True
                    import logging
                    logging.getLogger(__name__).info(
                        "beam decode loop auto-resolved to 'while' "
                        "(backend probe failed)")
                return "while"
        kind = "scan" if proxied else "chunked"
        if not _loop_kind_logged.get(kind):
            _loop_kind_logged[kind] = True
            import logging
            logging.getLogger(__name__).info(
                "beam decode loop auto-resolved to %r (proxied=%s)",
                kind, proxied)
        return kind
    if kind not in ("while", "scan", "chunked"):
        raise ValueError(
            f"beam loop kind must be while|scan|chunked|auto, got {kind!r} "
            f"(TS_BEAM_LOOP or the loop= argument)")
    return kind


class BeamSearchOutput(NamedTuple):
    """Best hypothesis per article (batch axis leading)."""

    tokens: Array  # [B, T_dec+1] extended-vocab ids, [0]=START
    length: Array  # [B] token count including START (== reference len(tokens))
    avg_log_prob: Array  # [B]
    attn_dists: Array  # [B, T_dec, T_enc] attention per generated token
    p_gens: Array  # [B, T_dec]


class _BeamState(NamedTuple):
    """Per-article search state, backpointer representation (ISSUE 7).

    History buffers (`parent_hist`/`tok_hist`/`attn_steps`/`pgen_steps`)
    are append-only: each step writes ONE column at `[:, t]` and nothing
    ever gathers them by parent — `_finalize_beam` backtracks the single
    winning trajectory at the end.  Their width is T+1: column T is a
    scratch column that masked (post-finish) loop iterations write into,
    and columns >= the finish step are dead — never read by the
    backtrack — so these buffers (and `dec_state`) stay OUT of the
    masked-update select in `_masked_scan_body` (see `_SELECT_FIELDS`).
    `attn_steps[:, t]` holds the step's raw attention rows indexed by the
    PRE-expansion (parent) beam slot; `tok_hist[:, t]`/`parent_hist[:, t]`
    are indexed by the post-expansion slot.
    """

    t: Array  # scalar int32: decode step (reference's `steps`)
    latest: Array  # [K] extended-vocab id of each live hyp's last token
    sum_lp: Array  # [K] total log prob of live hyps
    dec_state: Any  # model-family decode state; leaves lead with K
    n_res: Array  # scalar int32: filled result slots
    parent_hist: Array  # [K, T+1] int32 parent slot per step
    tok_hist: Array  # [K, T+1] int32 chosen token per step
    attn_steps: Array  # [K, T+1, T_enc] raw per-parent-slot attention rows
    pgen_steps: Array  # [K, T+1] raw per-parent-slot p_gen
    res_lp: Array  # [K+1] (slot K is a scratch slot)
    res_len: Array  # [K+1] int32, token count incl START
    res_t: Array  # [K+1] int32 finish step of each result
    res_par: Array  # [K+1] int32 parent (pre-expansion) slot at finish


def _init_beam_state(hps: HParams, T_enc: int, dec_state: Any,
                     attn_cols: Optional[int] = None) -> _BeamState:
    """The step-0 search state for one article (dec_state comes from the
    family's beam adapter; everything else is shape-only).

    attn_cols narrows the attention history to that many columns — the
    paged slot path (ISSUE 20) keeps a single scratch column per slot
    and scatters each step's row into the shared page pool instead of
    carrying the full [K, T+1, T_enc] buffer per resident."""
    K = hps.beam_size
    T = hps.max_dec_steps
    return _BeamState(
        t=jnp.zeros((), jnp.int32),
        latest=jnp.full((K,), START_ID, jnp.int32),
        sum_lp=jnp.zeros((K,), jnp.float32),
        dec_state=dec_state,
        n_res=jnp.zeros((), jnp.int32),
        parent_hist=jnp.zeros((K, T + 1), jnp.int32),
        tok_hist=jnp.zeros((K, T + 1), jnp.int32),
        attn_steps=jnp.zeros(
            (K, T + 1 if attn_cols is None else attn_cols, T_enc),
            jnp.float32),
        pgen_steps=jnp.zeros((K, T + 1), jnp.float32),
        res_lp=jnp.full((K + 1,), NEG, jnp.float32),
        res_len=jnp.ones((K + 1,), jnp.int32),
        res_t=jnp.zeros((K + 1,), jnp.int32),
        res_par=jnp.zeros((K + 1,), jnp.int32),
    )


def _beam_cond(hps: HParams):
    """The search-still-running predicate (reference's `steps <
    max_dec_steps and len(results) < beam_size`, beam_search.py:118)."""

    def cond(s: _BeamState):
        return jnp.logical_and(s.t < hps.max_dec_steps,
                               s.n_res < hps.beam_size)

    return cond


def _make_beam_body(params, hps: HParams, step_fn, enc_one, enc_mask,
                    ext_ids, attn_col_fn=None):
    """One decode step for one article, closed over its encoder view —
    shared verbatim by the batch search (_search_one) and the slot loops
    (step_slots_jit / step_slots_paged_jit), so the paths cannot drift.

    attn_col_fn(t) overrides the attention-history write column — the
    paged path (ISSUE 20) writes every step into its width-1 scratch
    column (index 0) and scatters that row into the page pool OUTSIDE
    this body; an explicit override, never out-of-bounds index
    semantics, keeps the write well-defined."""
    K = hps.beam_size
    V = hps.vocab_size
    S = K * 2 * K  # candidate count per step

    def body(s: _BeamState) -> _BeamState:
        latest = jnp.where(s.latest >= V, UNK_ID,
                           s.latest)  # beam_search.py:112
        step = step_fn(params, enc_one, enc_mask, ext_ids, s.t, latest,
                       s.dec_state)
        # candidate pool: every live hyp x its 2K continuations
        cand_lp = s.sum_lp[:, None] + step.topk_log_probs  # [K, 2K]
        # step 0: all hyps identical -> expand only hyp 0 (beam_search.py:125)
        first = jnp.arange(K)[:, None] == 0
        cand_lp = jnp.where(jnp.logical_or(s.t > 0, first), cand_lp, NEG)
        flat_lp = cand_lp.reshape(S)
        flat_tok = step.topk_ids.reshape(S)
        order = jnp.argsort(-flat_lp)  # stable descending
        srt_lp = flat_lp[order]
        srt_tok = flat_tok[order]
        parent = order // (2 * K)  # originating live hyp

        # sequential triage (beam_search.py:143-154) as cumsums: counts only
        # advance for selected candidates, and a candidate is processed only
        # while both pools are still short of K.
        is_stop = srt_tok == STOP_ID
        valid_stop = jnp.logical_and(is_stop, s.t >= hps.min_dec_steps)
        non_stop = jnp.logical_not(is_stop)
        live_rank = jnp.cumsum(non_stop)  # inclusive
        res_rank = jnp.cumsum(valid_stop)
        live_sel = non_stop & (live_rank <= K) & (s.n_res + res_rank < K)
        res_sel = valid_stop & (s.n_res + res_rank <= K) & (live_rank < K)

        # --- rebuild the live beam ---
        sel = jnp.argsort(jnp.logical_not(live_sel))[:K]  # first K selected
        ok = live_sel[sel]  # all True unless results filled first
        par = parent[sel]
        new_latest = srt_tok[sel]
        new_sum_lp = jnp.where(ok, srt_lp[sel], NEG)

        # --- append ONE backpointer column (no history gathers) ---
        # s.t == T only on masked post-horizon iterations; column T is
        # the scratch column those writes land in (never read back)
        parent_hist = s.parent_hist.at[:, s.t].set(par)
        tok_hist = s.tok_hist.at[:, s.t].set(new_latest)
        attn_col = s.t if attn_col_fn is None else attn_col_fn(s.t)
        attn_steps = s.attn_steps.at[:, attn_col].set(step.attn_dist)
        pgen_steps = s.pgen_steps.at[:, s.t].set(step.p_gen)

        # --- record finished hypotheses as scalar backpointers ---
        slot = jnp.where(res_sel, s.n_res + res_rank - 1, K)  # K = scratch
        res_lp = s.res_lp.at[slot].set(jnp.where(res_sel, srt_lp, NEG))
        res_len = s.res_len.at[slot].set(s.t + 2)  # START + t+1 generated
        res_t = s.res_t.at[slot].set(s.t)
        res_par = s.res_par.at[slot].set(parent)
        # scratch row K may hold garbage; restore invariants there
        res_lp = res_lp.at[K].set(NEG)

        return _BeamState(
            t=s.t + 1,
            latest=new_latest,
            sum_lp=new_sum_lp,
            dec_state=jax.tree_util.tree_map(lambda x: x[par], step.state),
            n_res=s.n_res + jnp.sum(res_sel).astype(jnp.int32),
            parent_hist=parent_hist,
            tok_hist=tok_hist,
            attn_steps=attn_steps,
            pgen_steps=pgen_steps,
            res_lp=res_lp,
            res_len=res_len,
            res_t=res_t,
            res_par=res_par,
        )

    return body


# the order-sensitive small leaves of _BeamState: the ONLY fields the
# masked scan select protects.  The history buffers and dec_state stay
# out on purpose (the decode byte diet's per-step win): a masked
# iteration's garbage writes land in dead columns — the scratch column T
# past the horizon, or the frozen-t column when the beam filled early,
# neither of which the finalize backtrack ever reads — and dec_state is
# never read again once cond(s) goes false.  Selecting them would re-read
# and re-write the full [K, T, T_enc] histories every masked step,
# reintroducing exactly the traffic the backpointer layout removes.
_SELECT_FIELDS = ("t", "latest", "sum_lp", "n_res",
                  "res_lp", "res_len", "res_t", "res_par")


def _masked_scan_body(cond, body):
    """Scan body with masked updates: once cond(s) goes false the
    order-sensitive state is carried through unchanged, so the result is
    token-exact with the while_loop (whose vmapped form masks every
    leaf).  body's garbage outputs past the horizon are discarded by the
    select (_SELECT_FIELDS) or land in dead history columns — see the
    _SELECT_FIELDS comment."""

    def scan_body(s, _):
        s2 = body(s)
        keep = cond(s)
        kept = {
            f: jax.tree_util.tree_map(
                lambda old, new: jnp.where(keep, new, old),
                getattr(s, f), getattr(s2, f))
            for f in _SELECT_FIELDS
        }
        return s2._replace(**kept), None

    return scan_body


def _search_one(params, hps: HParams, init_state_fn, step_fn, loop, chunk,
                enc_one, enc_mask, ext_ids) -> BeamSearchOutput:
    """Beam search for ONE article (un-batched inputs; vmapped below).

    enc_one: the family's per-article encoder view (pytree, no batch
    axis); enc_mask: [T_enc]; ext_ids: [T_enc] extended-vocab ids.
    init_state_fn/step_fn: the family's beam adapter (models/__init__).
    loop: 'while', 'scan', or 'chunked' (see _loop_kind); chunk: the
    chunked inner-scan length, or None for the TS_BEAM_CHUNK env default
    (read here, at trace time).
    """
    T = hps.max_dec_steps
    T_enc = enc_mask.shape[0]
    init = _init_beam_state(hps, T_enc, init_state_fn(params, enc_one))
    cond = _beam_cond(hps)
    body = _make_beam_body(params, hps, step_fn, enc_one, enc_mask, ext_ids)
    scan_body = _masked_scan_body(cond, body)

    if loop == "while":
        s = jax.lax.while_loop(cond, body, init)
    elif loop == "chunked":
        # while over fixed-size scan chunks: the RPC-proxied backend
        # charges ~1.4 ms per DYNAMIC loop iteration (host round trip on
        # the condition) but nothing per scan step, so ceil(T/C) dynamic
        # iterations buy while-style early exit (typical beams finish
        # well before max_dec_steps) at near-scan dispatch cost.  The
        # masked inner scan makes overshooting a chunk a no-op, so the
        # result stays token-exact with both other kinds.
        if chunk is None:  # env fallback, read at trace time
            chunk = resolved_chunk("chunked")
        C = min(max(int(chunk), 1), T)

        def chunk_body(s):
            s, _ = jax.lax.scan(scan_body, s, None, length=C)
            return s

        s = jax.lax.while_loop(cond, chunk_body, init)
    else:
        s, _ = jax.lax.scan(scan_body, init, None, length=T)

    return _finalize_beam(hps, s, T_enc)


def _finalize_beam(hps: HParams, s: _BeamState, T_enc: int,
                   ) -> BeamSearchOutput:
    """Rank the finished pool (falling back to the live beam), then
    reconstruct the ONE winning trajectory from the backpointer columns
    with a single reverse `lax.scan` over T — the reference's post-loop
    selection (beam_search.py:158-168) plus the ISSUE-7 backtrack pass.
    Shared by _search_one and unpack_slot_jit.
    """
    K = hps.beam_size
    T = hps.max_dec_steps
    # results empty -> fall back to the live beam (beam_search.py:158-160)
    use_live = s.n_res == 0
    live_len = s.t + 1  # START + t generated tokens
    pool_lp = jnp.where(use_live, jnp.concatenate([s.sum_lp, jnp.array([NEG])]),
                        s.res_lp)
    pool_len = jnp.where(use_live, jnp.full((K + 1,), live_len),
                         s.res_len)

    avg = pool_lp / pool_len.astype(jnp.float32)  # beam_search.py:77-79
    avg = jnp.where(pool_lp <= NEG / 2, NEG, avg)  # keep empty slots last
    best = jnp.argmax(avg)

    # Backtrack anchors: the step that produced the winner's LAST token,
    # the pre-expansion (parent) slot that produced it, and the token.
    # A live winner's last token came from post-expansion slot `best` at
    # step t-1 (t >= 1 always: the loop runs at least one step); a
    # result's came from the recorded (res_t, res_par) with a STOP token.
    live_slot = jnp.minimum(best, K - 1)  # best < K whenever live wins
    live_last_t = jnp.maximum(s.t - 1, 0)
    last_t = jnp.where(use_live, live_last_t, s.res_t[best])
    last_parent = jnp.where(use_live,
                            s.parent_hist[live_slot, live_last_t],
                            s.res_par[best])
    last_token = jnp.where(use_live, s.tok_hist[live_slot, live_last_t],
                           STOP_ID)

    def back(slot, t):
        # carry: the post-expansion slot the trajectory occupies at step
        # t (meaningful for t < last_t; re-anchored at t == last_t).
        at_last = t == last_t
        row_par = jnp.where(at_last, last_parent, s.parent_hist[slot, t])
        tok = jnp.where(at_last, last_token, s.tok_hist[slot, t])
        on_path = t <= last_t
        tok_out = jnp.where(on_path, tok, STOP_ID)  # STOP-fill past the end
        attn_row = jnp.where(on_path, s.attn_steps[row_par, t],
                             jnp.zeros((T_enc,), jnp.float32))
        pgen_val = jnp.where(on_path, s.pgen_steps[row_par, t], 0.0)
        return jnp.where(on_path, row_par, slot), (tok_out, attn_row,
                                                   pgen_val)

    _, (toks, attn, pgens) = jax.lax.scan(
        back, jnp.zeros((), jnp.int32), jnp.arange(T), reverse=True)
    tokens = jnp.concatenate([jnp.array([START_ID], jnp.int32), toks])
    return BeamSearchOutput(tokens=tokens,
                            length=pool_len[best],
                            avg_log_prob=avg[best],
                            attn_dists=attn,
                            p_gens=pgens)


def _search_batch(params, hps: HParams, arrays: Dict[str, Array],
                  loop: Optional[str] = None,
                  chunk: Optional[int] = None) -> BeamSearchOutput:
    """Encode a batch of B articles once, then vmap the per-article search.

    loop=None / chunk=None read TS_BEAM_LOOP / TS_BEAM_CHUNK at trace
    time (fine for callers that trace once, like the sharded step in
    parallel/mesh.py; jit callers that must react to env changes pass
    them explicitly — they are static cache-key arguments on
    run_beam_search_jit).
    """
    family = get_family(hps.model_family)
    enc_view = family.beam_encode(params, hps, arrays)
    init_state_fn, step_fn = family.beam_adapter(hps)
    fn = functools.partial(_search_one, params, hps, init_state_fn, step_fn,
                           _loop_kind(loop), chunk)
    return jax.vmap(fn)(enc_view, arrays["enc_padding_mask"],
                        arrays["enc_batch_extend_vocab"])


@functools.partial(jax.jit, static_argnames=("hps", "loop", "chunk"))
def run_beam_search_jit(params, hps: HParams, arrays: Dict[str, Array],
                        loop: Optional[str] = None,
                        chunk: Optional[int] = None) -> BeamSearchOutput:
    return _search_batch(params, hps, arrays, loop, chunk)


# --------------------------------------------------------------------------
# Slot-state search: the continuous-batching kernel set (ISSUE 6)
# + prefill/decode disaggregation (ISSUE 11)
# --------------------------------------------------------------------------
#
# The batch search above is all-or-nothing: one dispatch decodes B
# articles and returns when the SLOWEST finishes — the straggler barrier
# FastSeq (PAPERS.md) removes.  The slot API splits that dispatch into
# chunk-granular pieces over a persistent [slots, beam, ...] state so a
# host scheduler (serve/batcher.ContinuousBatcher) can retire finished
# articles and refill their slots between chunks.
#
# The request lifecycle is DISAGGREGATED into two stages (ISSUE 11):
#
#   PREFILL — encoder + cross-attention cache build, at the article's
#   micro-batcher bucket shape (config.parse_bucket_spec): one
#   prefill_jit compile per bucket, cost scaling with the bucket, never
#   with max_enc_steps.  The output is padded to the ONE resident width
#   and stamped with the article's true valid length.
#
#   DECODE — the persistent slot loop at one resident shape, carrying a
#   per-resident ``enc_valid_len``: each chunk's cross-attention runs a
#   conditional chain of encoder-key blocks bounded by the longest
#   ACTIVE resident's true length (see the family beam_adapter_masked
#   docs), so per-chunk bytes/FLOPs scale with real article lengths
#   instead of uniform padding — the FastSeq rule ("never let one
#   sequence's shape dictate the batch's cost") applied to the resident
#   set, at block granularity.
#
#     pre   = prefill_jit(params, hps, bucket_arrays)       # per admit
#     state = init_slots_jit(params, hps, zero_arrays)      # once
#     state = pack_slot_jit(params, hps, state, i, pre)     # admit
#     state, finished = step_slots_jit(params, hps, state, active, chunk)
#     out = unpack_slot_jit(hps, state, i)                  # retire
#
# Contracts:
#   * every DECODE kernel is shape-stable — slot index, active mask,
#     and valid lengths are TRACED arguments, so after the four warmup
#     compiles NO request, slot choice, occupancy pattern, or article
#     LENGTH pattern triggers a recompile; prefill_jit adds exactly one
#     compile per serve bucket (the warm set is 4 + len(buckets),
#     pinned by test);
#   * per-slot activity masks: an inactive slot's ORDER-SENSITIVE state
#     (_SELECT_FIELDS: step counter, live beam, result pool) is carried
#     through step_slots_jit unchanged — the same masked-update select
#     as the 'chunked' batch loop, so a resident article's trajectory is
#     token-exact with _search_one on the same inputs.  The history
#     buffers and dec_state are NOT select-protected (the decode byte
#     diet): a masked iteration writes garbage into them, confined to
#     dead regions — the frozen-t / scratch column and a never-again-read
#     dec_state — so an inactive slot's state is "unchanged" only where
#     unpack_slot_jit reads, and a slot's leaves are trustworthy ONLY
#     between pack and the step that finishes it (pack_slot_jit fully
#     overwrites on reuse; do not snapshot or inspect a slot's raw state
#     outside that window);
#   * pack/unpack happen ONLY at chunk boundaries — the host never
#     observes (or mutates) mid-chunk state.
#
# The per-article search itself is the SAME _make_beam_body /
# _init_beam_state / _finalize_beam code the batch path runs; the slot
# layer adds routing, not semantics.


class SlotState(NamedTuple):
    """Persistent decode state for `slots` resident articles.

    beam leaves lead with [slots, ...] (each slot an independent
    _BeamState); enc_view is the family's per-article encoder pytree
    stacked over slots; enc_mask/ext_ids are [slots, T_enc].  All
    shapes static: T_enc is fixed for the state's lifetime (one
    resident shape is what makes slot recycling shape-stable) — but a
    resident's COST is not: ``enc_valid_len`` carries each article's
    true length, the prefill stage fills only the valid prefix (zeros
    past it), and step_slots_jit bounds the cross-attention block chain
    by the longest active valid length (ISSUE 11).
    """

    beam: Any  # _BeamState with [slots, ...] leaves
    enc_view: Any  # family encoder view, [slots, ...] leaves
    enc_mask: Array  # [slots, T_enc]
    ext_ids: Array  # [slots, T_enc]
    enc_valid_len: Array  # [slots] int32 true (pre-padding) article length


class PrefillState(NamedTuple):
    """One prefilled article (leading axis 1), ready for pack_slot_jit:
    the encoder + cross-attention cache built at the article's BUCKET
    shape by prefill_jit, zero-padded out to the resident width, plus
    the true valid length the decode stage masks by.  The zero tail is
    semantically dead (behind the valid-length mask) — padding here is
    what keeps pack_slot_jit at ONE compile across buckets."""

    enc_view: Any  # family encoder view, [1, T_enc_max, ...] leaves
    enc_mask: Array  # [1, T_enc_max]
    ext_ids: Array  # [1, T_enc_max]
    enc_valid_len: Array  # [1] int32


def _init_slot_beams(params, hps: HParams, enc_view, enc_mask,
                     attn_cols: Optional[int] = None):
    """vmapped step-0 beam state for a stack of articles."""
    family = get_family(hps.model_family)
    init_state_fn, _ = family.beam_adapter(hps)

    def one(enc_one, mask):
        return _init_beam_state(hps, mask.shape[0],
                                init_state_fn(params, enc_one),
                                attn_cols=attn_cols)

    return jax.vmap(one)(enc_view, enc_mask)


@functools.partial(jax.jit, static_argnames=("hps",))
def init_slots_jit(params, hps: HParams,
                   arrays: Dict[str, Array]) -> SlotState:
    """The all-empty persistent state from a [slots, T_enc] arrays dict
    (zeros are fine: inactive slots are never stepped unmasked and are
    fully overwritten by pack_slot_jit before first use)."""
    family = get_family(hps.model_family)
    enc_view = family.beam_encode(params, hps, arrays)
    slots = arrays["enc_padding_mask"].shape[0]
    return SlotState(
        beam=_init_slot_beams(params, hps, enc_view,
                              arrays["enc_padding_mask"]),
        enc_view=enc_view,
        enc_mask=arrays["enc_padding_mask"],
        ext_ids=arrays["enc_batch_extend_vocab"],
        enc_valid_len=jnp.zeros((slots,), jnp.int32))


@functools.partial(jax.jit, static_argnames=("hps",))
def prefill_jit(params, hps: HParams,
                arrays: Dict[str, Array]) -> PrefillState:
    """The PREFILL stage (ISSUE 11): encoder + cross-attention cache for
    ONE article at its BUCKET shape — ``arrays`` leaves are [1, bucket]
    — then zero-padded to the resident width (hps.max_enc_steps) so
    pack_slot_jit stays at one compile.  The jit cache keys on the
    input shapes, so the warm set is exactly one executable per serve
    bucket; the encoder work (the LSTM scan / the T_enc^2 encoder
    self-attention — the cost the one-resident-shape engine used to pay
    at FULL width for every admission) scales with the bucket.

    Both families' encoders are pad-invariant (masked LSTM
    carry-through / masked softmax), so the valid prefix of the bucket
    encode is bitwise the valid prefix of a full-width encode — parity
    with the batch search is by construction, not by tolerance."""
    family = get_family(hps.model_family)
    enc_view = family.pad_enc_view(family.beam_encode(params, hps, arrays),
                                   hps.max_enc_steps)
    T = hps.max_enc_steps

    def pad_t(x):
        if x.shape[1] >= T:
            return x
        return jnp.pad(x, [(0, 0), (0, T - x.shape[1])])

    return PrefillState(
        enc_view=enc_view,
        enc_mask=pad_t(arrays["enc_padding_mask"]),
        ext_ids=pad_t(arrays["enc_batch_extend_vocab"]),
        enc_valid_len=arrays["enc_lens"].astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("hps",))
def pack_slot_jit(params, hps: HParams, state: SlotState, idx,
                  pre: PrefillState) -> SlotState:
    """Admit ONE PREFILLED article into slot `idx` — the
    pack-with-length-mask (ISSUE 11): scatter the padded encoder view,
    initialize the slot's search, and stamp the resident's true valid
    length (what the decode stage's block chain and attention masks key
    on).  `idx` is traced — one compile serves every slot, and because
    prefill already normalized every bucket to the resident width, one
    compile serves every bucket too."""
    beam1 = _init_slot_beams(params, hps, pre.enc_view, pre.enc_mask)

    def write(dst, src):
        return dst.at[idx].set(src[0])

    return SlotState(
        beam=jax.tree_util.tree_map(write, state.beam, beam1),
        enc_view=jax.tree_util.tree_map(write, state.enc_view,
                                        pre.enc_view),
        enc_mask=state.enc_mask.at[idx].set(pre.enc_mask[0]),
        ext_ids=state.ext_ids.at[idx].set(pre.ext_ids[0]),
        enc_valid_len=state.enc_valid_len.at[idx].set(
            pre.enc_valid_len[0]))


@functools.partial(jax.jit, static_argnames=("hps", "chunk"))
def step_slots_jit(params, hps: HParams, state: SlotState, active,
                   chunk: int):
    """Advance every ACTIVE slot by up to `chunk` masked decode steps.

    active: [slots] bool.  Returns (state', finished) where finished[i]
    marks an active slot whose search is done (horizon reached or beam
    full of results) — the host retires it via unpack_slot_jit and may
    refill.  Inactive slots run the same chunk on garbage state (the
    cost of shape stability): every ORDER-SENSITIVE update is discarded
    by the _SELECT_FIELDS mask — a NaN in a dead lane never escapes
    into the selected leaves — while the dead lane's history columns
    and dec_state DO take garbage writes, all confined to regions
    unpack_slot_jit never reads and fully overwritten by the next
    pack_slot_jit (see the slot-contract comment above).

    Length-masked decode (ISSUE 11): the chunk's cross-attention block
    chain is bounded by ``nb`` = ceil(max active enc_valid_len /
    resolve_enc_block) — a TRACED scalar, uniform across the vmapped
    slots, so the conditional chain survives the vmap as real branches
    and one compile serves every length pattern.  Work executed per
    chunk scales with the longest ACTIVE resident's true article
    length; shorter co-residents' extra blocks are masked to the same
    energy floor the dense path gives padding, so trajectories stay
    token-exact with the batch search."""
    family = get_family(hps.model_family)
    _, step_fn = family.beam_adapter_masked(hps)
    cond = _beam_cond(hps)
    from textsummarization_on_flink_tpu.config import resolve_enc_block

    block = resolve_enc_block(hps)
    valid = jnp.where(active, state.enc_valid_len,
                      jnp.zeros_like(state.enc_valid_len))
    nb = (jnp.max(valid) + block - 1) // block  # scalar, traced

    def one(beam, act, enc_one, mask, ext):
        def step_nb(p, e, m, x, t, latest, s):
            return step_fn(p, e, m, x, nb, t, latest, s)

        body = _make_beam_body(params, hps, step_nb, enc_one, mask, ext)

        def masked_cond(s):
            return jnp.logical_and(act, cond(s))

        scan_body = _masked_scan_body(masked_cond, body)
        s, _ = jax.lax.scan(scan_body, beam, None, length=chunk)
        return s, jnp.logical_and(act, jnp.logical_not(cond(s)))

    beam, finished = jax.vmap(one)(state.beam, active, state.enc_view,
                                   state.enc_mask, state.ext_ids)
    return state._replace(beam=beam), finished


@functools.partial(jax.jit, static_argnames=("hps",))
def unpack_slot_jit(hps: HParams, state: SlotState, idx) -> BeamSearchOutput:
    """The finished hypothesis for slot `idx` (no batch axis), ranked
    exactly like the batch path's tail.  `idx` is traced — one compile.
    The slot is NOT cleared here; the host's activity mask retires it
    and the next pack overwrites the state."""
    s = jax.tree_util.tree_map(lambda x: x[idx], state.beam)
    return _finalize_beam(hps, s, state.enc_mask.shape[1])


# --------------------------------------------------------------------------
# Paged resident state: the block-granular slot arena (ISSUE 20)
# --------------------------------------------------------------------------
#
# PR 11's length masks cut the slot engine's COMPUTE to true article
# lengths, but every resident still owned full-width encoder-axis
# buffers: slot COUNT stayed provisioned for the worst-case article.
# The paged kernel set below drops the per-slot reservation to page
# granularity — the vLLM/PagedAttention block-table idea applied to this
# engine's T_enc axis:
#
#   * every enc-axis leaf of the resident state — the family encoder
#     view (for tf/aan that IS the cross-attention KV cache), the
#     extended-vocab ids, and the [K, T+1, T_enc] attention history —
#     becomes a POOL of `resolve_enc_block`-row pages shared by all
#     slots, sized by the arena (decode/arena.PageArena) instead of
#     slots x max_enc_steps;
#   * each slot's pages are named by a per-slot PAGE-TABLE row — int32
#     DATA passed as a traced argument, never shape: page-table
#     contents, occupancy, and allocation pattern can never recompile
#     (the PR 6/11 discipline), and the warm set stays 4 decode
#     compiles + one prefill per bucket;
#   * page index P (== arena capacity) is the SCRATCH page: every
#     unused table entry points at it, inactive slots are routed to it
#     inside the kernels, and its contents are garbage by contract —
#     exactly the dead-column story the byte-diet histories already
#     tell (see _SELECT_FIELDS);
#   * dec_state stays DENSE on purpose: its big leaves (the tf
#     self-attention KV cache) run over the DECODE axis, which the
#     bimodal mix does not vary — paging them buys nothing at this
#     workload while doubling the scatter traffic.  pg's [K, T_enc]
#     coverage is enc-axis but second-order (one f32 row vs the 2H-wide
#     encoder states); it rides dense too.
#
# Token-exactness is by construction, not tolerance: gathers through
# the table reconstruct each ACTIVE slot's exact dense view (garbage
# beyond a slot's valid pages sits behind the PR 11 valid-length masks,
# whose exact-zero softmax contributes 0.0), and the per-step attention
# row is scattered into the pool at the same (slot, t) coordinates the
# dense path writes — the parity suite pins all three families bitwise
# at page boundaries.
#
# Lifecycle (host side in decode/decoder.SlotDecodeEngine):
#   pages = resolve_arena_pages(hps, paged_page_bytes(params, hps))
#   state = init_slots_paged_jit(params, hps, zeros, pages=pages)
#   row   = arena.alloc(ceil(len/block)) padded with scratch    # admit
#   state = pack_slot_paged_jit(params, hps, state, i, pre, row)
#   state, fin = step_slots_paged_jit(params, hps, state, active,
#                                     table, chunk)   # table: [slots, B]
#   out   = unpack_slot_paged_jit(hps, state, i, row); arena.free(row)


class PagedSlotState(NamedTuple):
    """Persistent decode state for the paged engine (ISSUE 20).

    Relative to SlotState: the enc-axis leaves live in shared page
    pools with one extra SCRATCH page at index [-1]; ``enc_rest`` keeps
    the family enc_view's TREE STRUCTURE with each pooled leaf squeezed
    to width 0 on its time axis (zero bytes, but the treedef and the
    non-time leaves — e.g. pointer-generator's dec_in_state — survive
    in place, so the kernels can rebuild the exact dense view by
    re-probing `pad_enc_view`, the same single source of truth
    prefill's padding uses).  The beam's attention history is a width-1
    scratch column; each step's row is scattered into ``attn_pool`` at
    the slot's pages.  ``enc_mask``/``enc_valid_len`` stay dense —
    they ARE the masks that make page garbage contribute exact zeros.
    """

    beam: Any  # _BeamState, [slots, ...] leaves; attn_steps [slots,K,1,T_enc]
    enc_rest: Any  # enc_view tree; pooled leaves squeezed to time-width 0
    enc_pages: Any  # tuple of pools [pages+1, block, *tail], pool [-1]=scratch
    ext_pool: Array  # [pages+1, block] int32 extended-vocab ids
    attn_pool: Array  # [pages+1, K, T+1, block] f32 attention history pages
    enc_mask: Array  # [slots, T_enc]
    enc_valid_len: Array  # [slots] int32


def _pool_spec(hps: HParams):
    """(block, pages-per-slot-max, padded width) of the page layout —
    block is resolve_enc_block (pages ARE the length-mask blocks, so
    the PR 11 block chain and the arena agree on granularity)."""
    from textsummarization_on_flink_tpu.config import resolve_enc_block

    block = resolve_enc_block(hps)
    b_max = -(-hps.max_enc_steps // block)
    return block, b_max, block * b_max


def _enc_time_axes(hps: HParams, enc_view):
    """Per-leaf encoder-time axis of a (possibly width-0) enc_view,
    probed by SHAPE through the family's own pad_enc_view: pad the view
    past any real width and see which axis grew.  None marks a leaf
    with no time axis (stays dense).  Pure eval_shape — runs at trace
    time, costs nothing, and cannot drift from the padding the prefill
    path actually performs."""
    family = get_family(hps.model_family)
    t_probe = hps.max_enc_steps + 17
    padded = jax.eval_shape(lambda v: family.pad_enc_view(v, t_probe),
                            enc_view)
    axes = []
    for a, b in zip(jax.tree_util.tree_leaves(enc_view),
                    jax.tree_util.tree_leaves(padded)):
        if tuple(a.shape) == tuple(b.shape):
            axes.append(None)
            continue
        diff = [i for i, (x, y) in enumerate(zip(a.shape, b.shape))
                if x != y]
        if len(diff) != 1:
            raise ValueError(
                f"pad_enc_view changed more than one axis "
                f"({a.shape} -> {b.shape}); cannot page this leaf")
        axes.append(diff[0])
    return tuple(axes)


def _leaf_to_pages(leaf, ta: int, block: int, b_max: int):
    """One prefilled [1, ...] enc leaf -> its [b_max, block, *tail] page
    stack (time axis moved out front, zero-padded to the page grid)."""
    x = jnp.moveaxis(leaf, ta, 1)[0]  # [T_enc, *tail]
    pad = b_max * block - x.shape[0]
    if pad:
        x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
    return x.reshape((b_max, block) + x.shape[1:])


def _pages_to_leaf(pool, pages, ta: int, T_enc: int):
    """Gather a dense [slots, ...] enc leaf back out of its pool through
    the page table (pages: [slots, b_max] int32; scratch rows carry
    garbage that sits behind the valid-length masks)."""
    g = pool[pages]  # [slots, b_max, block, *tail]
    g = g.reshape((g.shape[0], g.shape[1] * g.shape[2]) + g.shape[3:])
    return jnp.moveaxis(g[:, :T_enc], 1, ta)


def paged_page_bytes(params, hps: HParams) -> int:
    """Bytes ONE arena page spans across all pools (enc-view pages +
    ext-id page + attention-history page) — the unit
    config.resolve_arena_pages divides the HBM byte budget by.  Pure
    eval_shape on the family's encoder view; jax-free callers pass the
    params tree they already hold."""
    block, _, _ = _pool_spec(hps)
    family = get_family(hps.model_family)
    probe = {
        "enc_batch": jax.ShapeDtypeStruct((1, hps.max_enc_steps),
                                          jnp.int32),
        "enc_lens": jax.ShapeDtypeStruct((1,), jnp.int32),
        "enc_padding_mask": jax.ShapeDtypeStruct((1, hps.max_enc_steps),
                                                 jnp.float32),
        "enc_batch_extend_vocab": jax.ShapeDtypeStruct(
            (1, hps.max_enc_steps), jnp.int32),
    }
    view = jax.eval_shape(
        lambda p, a: family.beam_encode(p, hps, a), params, probe)
    axes = _enc_time_axes(hps, view)
    total = 0
    for leaf, ta in zip(jax.tree_util.tree_leaves(view), axes):
        if ta is None:
            continue
        tail = int(np.prod([d for i, d in enumerate(leaf.shape)
                            if i not in (0, ta)], dtype=np.int64))
        total += block * tail * jnp.dtype(leaf.dtype).itemsize
    total += block * 4  # ext_pool page (int32)
    total += hps.beam_size * (hps.max_dec_steps + 1) * block * 4  # attn f32
    return int(total)


@functools.partial(jax.jit, static_argnames=("hps", "pages"))
def init_slots_paged_jit(params, hps: HParams, arrays: Dict[str, Array],
                         pages: int) -> PagedSlotState:
    """The all-empty paged state: pools sized by the arena (`pages` is
    the ONE static knob — fixed for the engine's lifetime, so this
    stays one compile), everything else zeros.  Pool row `pages` is the
    scratch page."""
    family = get_family(hps.model_family)
    enc_view = family.beam_encode(params, hps, arrays)
    slots = arrays["enc_padding_mask"].shape[0]
    block, b_max, _ = _pool_spec(hps)
    axes = _enc_time_axes(hps, enc_view)
    leaves, treedef = jax.tree_util.tree_flatten(enc_view)
    rest, pools = [], []
    for leaf, ta in zip(leaves, axes):
        if ta is None:
            rest.append(leaf)
            continue
        tail = tuple(d for i, d in enumerate(leaf.shape)
                     if i not in (0, ta))
        pools.append(jnp.zeros((pages + 1, block) + tail, leaf.dtype))
        rest.append(jax.lax.slice_in_dim(leaf, 0, 0, axis=ta))
    K, T = hps.beam_size, hps.max_dec_steps
    return PagedSlotState(
        beam=_init_slot_beams(params, hps, enc_view,
                              arrays["enc_padding_mask"], attn_cols=1),
        enc_rest=jax.tree_util.tree_unflatten(treedef, rest),
        enc_pages=tuple(pools),
        ext_pool=jnp.zeros((pages + 1, block), jnp.int32),
        attn_pool=jnp.zeros((pages + 1, K, T + 1, block), jnp.float32),
        enc_mask=arrays["enc_padding_mask"],
        enc_valid_len=jnp.zeros((slots,), jnp.int32))


@functools.partial(jax.jit, static_argnames=("hps",))
def pack_slot_paged_jit(params, hps: HParams, state: PagedSlotState, idx,
                        pre: PrefillState, row) -> PagedSlotState:
    """Admit ONE prefilled article into slot `idx` with page-table row
    `row` ([b_max] int32 — the slot's freshly allocated pages, padded
    with the scratch id).  `idx` and `row` are both traced: one compile
    serves every slot, every bucket, AND every allocation pattern.
    Unused row entries all scatter into the scratch page (duplicate
    writes there are unordered and don't matter — scratch holds garbage
    by contract); stale attn pages from a page's previous tenant need
    no clearing, because unpack masks columns past the new tenant's
    valid length and the finalize backtrack masks steps past its
    horizon."""
    block, b_max, _ = _pool_spec(hps)
    axes = _enc_time_axes(hps, pre.enc_view)
    beam1 = _init_slot_beams(params, hps, pre.enc_view, pre.enc_mask,
                             attn_cols=1)

    def write(dst, src):
        return dst.at[idx].set(src[0])

    leaves = jax.tree_util.tree_leaves(pre.enc_view)
    rest_leaves, treedef = jax.tree_util.tree_flatten(state.enc_rest)
    rest_new, pool_new = [], []
    pool_it = iter(state.enc_pages)
    for leaf, rest_leaf, ta in zip(leaves, rest_leaves, axes):
        if ta is None:
            rest_new.append(rest_leaf.at[idx].set(leaf[0]))
            continue
        pool = next(pool_it)
        pool_new.append(pool.at[row].set(
            _leaf_to_pages(leaf, ta, block, b_max)))
        rest_new.append(rest_leaf)  # width-0: nothing to write
    ext = pre.ext_ids[0]
    pad = b_max * block - ext.shape[0]
    if pad:
        ext = jnp.pad(ext, (0, pad))
    return PagedSlotState(
        beam=jax.tree_util.tree_map(write, state.beam, beam1),
        enc_rest=jax.tree_util.tree_unflatten(treedef, rest_new),
        enc_pages=tuple(pool_new),
        ext_pool=state.ext_pool.at[row].set(ext.reshape(b_max, block)),
        attn_pool=state.attn_pool,
        enc_mask=state.enc_mask.at[idx].set(pre.enc_mask[0]),
        enc_valid_len=state.enc_valid_len.at[idx].set(
            pre.enc_valid_len[0]))


@functools.partial(jax.jit, static_argnames=("hps", "chunk"))
def step_slots_paged_jit(params, hps: HParams, state: PagedSlotState,
                         active, table, chunk: int):
    """Advance every ACTIVE slot by up to `chunk` masked decode steps,
    gathering encoder state through the page table (`table`: [slots,
    b_max] int32, traced DATA — occupancy and allocation pattern can
    never recompile).

    Structure: the dense per-slot encoder views are gathered ONCE per
    chunk (loop-invariant — the gather cost amortizes over the chunk's
    steps), then a top-level scan runs the chunk with a vmapped
    per-slot masked step inside — scan-of-vmap instead of the dense
    kernel's vmap-of-scan, which commutes (slots are independent; nb is
    computed once outside either way) but exposes each step's
    attention row for ONE scatter into the shared pool at (slot pages,
    pre-step t).  Inactive slots' table rows are routed to the scratch
    page before either the gather or the scatter, so a harvested slot's
    stale table can never read from — or write garbage into — pages the
    arena has re-issued to a new tenant.  Masked (post-finish) lanes
    scatter garbage at their frozen t — a dead column of their OWN
    pages, exactly the column the dense kernel lets them dirty."""
    family = get_family(hps.model_family)
    _, step_fn = family.beam_adapter_masked(hps)
    cond = _beam_cond(hps)
    from textsummarization_on_flink_tpu.config import resolve_enc_block

    block = resolve_enc_block(hps)
    _, b_max, t_pad = _pool_spec(hps)
    T_enc = state.enc_mask.shape[1]
    slots = active.shape[0]
    K, T = hps.beam_size, hps.max_dec_steps
    scratch = state.attn_pool.shape[0] - 1  # page id P, static
    pages = jnp.where(active[:, None], table, scratch)

    valid = jnp.where(active, state.enc_valid_len,
                      jnp.zeros_like(state.enc_valid_len))
    nb = (jnp.max(valid) + block - 1) // block  # scalar, traced

    # rebuild the dense enc views once per chunk (loop-invariant)
    axes = _enc_time_axes(hps, state.enc_rest)
    rest_leaves, treedef = jax.tree_util.tree_flatten(state.enc_rest)
    dense_leaves = []
    pool_it = iter(state.enc_pages)
    for leaf, ta in zip(rest_leaves, axes):
        if ta is None:
            dense_leaves.append(leaf)
            continue
        dense_leaves.append(_pages_to_leaf(next(pool_it), pages, ta,
                                           T_enc))
    enc_view = jax.tree_util.tree_unflatten(treedef, dense_leaves)
    ext = state.ext_pool[pages].reshape(slots, t_pad)[:, :T_enc]

    def one_step(beam, act, enc_one, mask, ext_one):
        def step_nb(p, e, m, x, t, latest, s):
            return step_fn(p, e, m, x, nb, t, latest, s)

        body = _make_beam_body(params, hps, step_nb, enc_one, mask,
                               ext_one, attn_col_fn=lambda t: 0)

        def masked_cond(s):
            return jnp.logical_and(act, cond(s))

        s2, _ = _masked_scan_body(masked_cond, body)(beam, None)
        return s2

    flat_pages = pages.reshape(-1)  # [slots*b_max]

    def chunk_body(carry, _):
        beams, attn_pool = carry
        t_old = beams.t  # [slots] pre-step write column (t <= T always)
        beams2 = jax.vmap(one_step)(beams, active, enc_view,
                                    state.enc_mask, ext)
        attn = beams2.attn_steps[:, :, 0, :]  # [slots, K, T_enc]
        pad = t_pad - T_enc
        if pad:
            attn = jnp.pad(attn, [(0, 0), (0, 0), (0, pad)])
        vals = attn.reshape(slots, K, b_max, block).transpose(0, 2, 1, 3)
        attn_pool = attn_pool.at[flat_pages, :,
                                 jnp.repeat(t_old, b_max)].set(
            vals.reshape(slots * b_max, K, block))
        return (beams2, attn_pool), None

    (beam, attn_pool), _ = jax.lax.scan(
        chunk_body, (state.beam, state.attn_pool), None, length=chunk)
    finished = jnp.logical_and(active,
                               jnp.logical_not(jax.vmap(cond)(beam)))
    return state._replace(beam=beam, attn_pool=attn_pool), finished


@functools.partial(jax.jit, static_argnames=("hps",))
def unpack_slot_paged_jit(hps: HParams, state: PagedSlotState, idx,
                          row) -> BeamSearchOutput:
    """The finished hypothesis for slot `idx`: gather the slot's
    attention pages back into the dense [K, T+1, T_enc] history the
    finalize backtrack expects (`row` is the slot's CURRENT table row —
    the host frees the pages only after this call), zero columns past
    the valid length (where the dense path's masked softmax wrote exact
    zeros but a recycled page holds a previous tenant's rows), and run
    the SAME _finalize_beam as every other path."""
    K, T = hps.beam_size, hps.max_dec_steps
    _, b_max, t_pad = _pool_spec(hps)
    T_enc = state.enc_mask.shape[1]
    s = jax.tree_util.tree_map(lambda x: x[idx], state.beam)
    ap = state.attn_pool[row]  # [b_max, K, T+1, block]
    attn = jnp.moveaxis(ap, 0, 2).reshape(K, T + 1, t_pad)[:, :, :T_enc]
    valid = state.enc_valid_len[idx]
    attn = jnp.where(jnp.arange(T_enc)[None, None, :] < valid, attn, 0.0)
    return _finalize_beam(hps, s._replace(attn_steps=attn), T_enc)


def resolved_chunk(loop: str) -> Optional[int]:
    """The effective chunked inner-scan length, resolved from the env —
    pass this to run_beam_search_jit so the chunk size participates in
    the jit cache key (an env change between calls would otherwise be
    silently ignored by the cached executable).  The default lives in
    config.beam_chunk_from_env (single source, shared with bench.py's
    config fingerprint)."""
    if loop != "chunked":
        return None
    from textsummarization_on_flink_tpu.config import beam_chunk_from_env

    return beam_chunk_from_env()


def run_beam_search(params, hps: HParams, arrays: Dict[str, np.ndarray],
                    ) -> BeamSearchOutput:
    """Host entry: one compiled dispatch decodes the whole batch.

    Returns host numpy BeamSearchOutput; callers strip START/[STOP] and map
    ids back to words (decode/decoder.py, mirroring decode.py:109-119).
    """
    loop = _loop_kind()
    from textsummarization_on_flink_tpu import obs
    from textsummarization_on_flink_tpu.obs import profile as profile_lib

    # the shared compile ledger (obs/profile.py, ISSUE 16) carries the
    # jit-cache hit/miss telemetry this site used to hand-roll: cache
    # growth across the call = a fresh trace/compile
    chunk = resolved_chunk(loop)
    out = profile_lib.compiled_call(
        obs.registry_for(hps), "decode/beam_search_jit",
        run_beam_search_jit, params, hps, arrays,
        key=(loop, chunk), phase="decode/beam_search",
        loop=loop, chunk=chunk)
    return BeamSearchOutput(*[np.asarray(x) for x in out])
