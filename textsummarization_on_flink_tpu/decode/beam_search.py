"""On-device batched beam search.

Semantics parity with the reference's Python-side beam search
(/root/reference/src/main/python/pointer-generator/beam_search.py), but the
entire search runs inside one jitted on-device loop per dispatch — a
`lax.scan` over max_dec_steps with masked updates, or a `lax.while_loop`
with early exit, auto-selected per backend (TS_BEAM_LOOP, see
_loop_kind) — instead of ~100 `sess.run` round trips per article
(SURVEY.md §3.4):

  * at step 0 only the first (all-identical) hypothesis is expanded
    (beam_search.py:125 `num_orig_hyps`);
  * each live hypothesis proposes `2*beam_size` continuations
    (beam_search.py:127-141, model.py:280-285);
  * candidates are processed in descending score order: a STOP candidate
    moves to the results pool only if at least `min_dec_steps` tokens were
    generated (earlier STOPs are *discarded*), anything else refills the
    live beam, and processing halts once either pool holds `beam_size`
    entries (beam_search.py:143-154);
  * the loop ends when `beam_size` results exist or `max_dec_steps` is
    reached; an empty results pool falls back to the live beam
    (beam_search.py:158-162);
  * final ranking is by length-normalized total log-prob, where the length
    includes the START token like the reference's
    `len(self.tokens)` (beam_search.py:71-79,164-168).

Because live hypotheses all share the same length at any step, ordering by
total log-prob during the search equals the reference's ordering by average
log-prob; the average only matters for the final cross-length ranking.

TPU-first details: all shapes are static — tokens/results live in
`[beam, max_dec_steps+1]` buffers, the per-step candidate triage is a pure
cumulative-sum computation over the `beam*2*beam` sorted candidates (no
data-dependent Python), and a whole batch of B articles is searched per
dispatch via `vmap`.  OOV ids are mapped back to UNK before the embedding
lookup inside the loop (beam_search.py:112).

Model-family-agnostic: the search drives the (init_state, step) beam
adapter of ``hps.model_family`` (models/__init__.get_family), carrying the
model's decode state — LSTM cell + coverage, or a transformer KV cache —
as an opaque pytree whose leaves lead with the beam axis.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import START_ID, STOP_ID, UNK_ID
from textsummarization_on_flink_tpu.models import get_family

Array = jax.Array

NEG = -1e30  # effectively -inf, without inf-inf NaN hazards

_loop_kind_logged: Dict[str, bool] = {}


def _loop_kind(kind: Optional[str] = None) -> str:
    """Resolve the decode-loop construct: 'while' (early exit once every
    article's beam finishes), 'scan' (fixed max_dec_steps trip count),
    or 'chunked' (while over TS_BEAM_CHUNK-step scan chunks — early exit
    at chunk granularity with only ceil(T/C) dynamic iterations).

    All three produce IDENTICAL results: under vmap a while_loop already
    applies masked per-article updates until the slowest article's cond
    goes false; scan merely fixes the trip count at the worst case, and
    chunked interleaves the two at chunk granularity.  What
    scan buys is freedom from per-iteration host involvement — on an
    RPC-proxied backend (the tunneled axon TPU) every dynamic-condition
    loop iteration costs ~1.4 ms of round trip, ~140 ms per batch at the
    reference's max_dec_steps=100, while a scan dispatches once.  On a
    directly attached backend while's condition evaluates on device, so
    its early exit is free and saves the tail steps.

    TS_BEAM_LOOP=while|scan|chunked|auto; auto (the default) picks scan
    when the backend is the RPC-proxied axon plugin, else while
    (chunked is opt-in until the decode sweep row proves it).  The
    resolved kind is logged once so a mis-detection is visible in decode
    logs (ADVICE r2: JAX_PLATFORMS alone misses plugin
    auto-registration).
    """
    kind = (kind or os.environ.get("TS_BEAM_LOOP", "auto")).lower()
    if kind == "auto":
        proxied = "axon" in os.environ.get("JAX_PLATFORMS", "").lower()
        if not proxied:
            # the plugin may have been picked up via auto-registration or
            # JAX_PLATFORM_NAME rather than JAX_PLATFORMS; ask jax which
            # backend actually resolved (cheap after first init)
            try:
                proxied = "axon" in jax.default_backend().lower()
            except Exception:  # tslint: disable=TS005 — ANY backend-init failure must fall through to the 'while' default, never break decode
                pass
        kind = "scan" if proxied else "while"
        if not _loop_kind_logged.get(kind):
            _loop_kind_logged[kind] = True
            import logging
            logging.getLogger(__name__).info(
                "beam decode loop auto-resolved to %r (proxied=%s)",
                kind, proxied)
        return kind
    if kind not in ("while", "scan", "chunked"):
        raise ValueError(
            f"beam loop kind must be while|scan|chunked|auto, got {kind!r} "
            f"(TS_BEAM_LOOP or the loop= argument)")
    return kind


class BeamSearchOutput(NamedTuple):
    """Best hypothesis per article (batch axis leading)."""

    tokens: Array  # [B, T_dec+1] extended-vocab ids, [0]=START
    length: Array  # [B] token count including START (== reference len(tokens))
    avg_log_prob: Array  # [B]
    attn_dists: Array  # [B, T_dec, T_enc] attention per generated token
    p_gens: Array  # [B, T_dec]


class _BeamState(NamedTuple):
    t: Array  # scalar int32: decode step (reference's `steps`)
    tokens: Array  # [K, T+1]
    sum_lp: Array  # [K] total log prob of live hyps
    dec_state: Any  # model-family decode state; leaves lead with K
    attn_hist: Array  # [K, T, T_enc]
    pgen_hist: Array  # [K, T]
    n_res: Array  # scalar int32: filled result slots
    res_tokens: Array  # [K+1, T+1] (row K is a scratch slot)
    res_lp: Array  # [K+1]
    res_len: Array  # [K+1] int32, token count incl START
    res_attn: Array  # [K+1, T, T_enc]
    res_pgen: Array  # [K+1, T]


def _init_beam_state(hps: HParams, T_enc: int, dec_state: Any) -> _BeamState:
    """The step-0 search state for one article (dec_state comes from the
    family's beam adapter; everything else is shape-only)."""
    K = hps.beam_size
    T = hps.max_dec_steps
    return _BeamState(
        t=jnp.zeros((), jnp.int32),
        tokens=jnp.full((K, T + 1), STOP_ID, jnp.int32).at[:, 0].set(START_ID),
        sum_lp=jnp.zeros((K,), jnp.float32),
        dec_state=dec_state,
        attn_hist=jnp.zeros((K, T, T_enc), jnp.float32),
        pgen_hist=jnp.zeros((K, T), jnp.float32),
        n_res=jnp.zeros((), jnp.int32),
        res_tokens=jnp.zeros((K + 1, T + 1), jnp.int32),
        res_lp=jnp.full((K + 1,), NEG, jnp.float32),
        res_len=jnp.ones((K + 1,), jnp.int32),
        res_attn=jnp.zeros((K + 1, T, T_enc), jnp.float32),
        res_pgen=jnp.zeros((K + 1, T), jnp.float32),
    )


def _beam_cond(hps: HParams):
    """The search-still-running predicate (reference's `steps <
    max_dec_steps and len(results) < beam_size`, beam_search.py:118)."""

    def cond(s: _BeamState):
        return jnp.logical_and(s.t < hps.max_dec_steps,
                               s.n_res < hps.beam_size)

    return cond


def _make_beam_body(params, hps: HParams, step_fn, enc_one, enc_mask,
                    ext_ids):
    """One decode step for one article, closed over its encoder view —
    shared verbatim by the batch search (_search_one) and the slot loop
    (step_slots_jit), so the two paths cannot drift."""
    K = hps.beam_size
    V = hps.vocab_size
    S = K * 2 * K  # candidate count per step

    def body(s: _BeamState) -> _BeamState:
        latest = s.tokens[:, s.t]  # [K]
        latest = jnp.where(latest >= V, UNK_ID, latest)  # beam_search.py:112
        step = step_fn(params, enc_one, enc_mask, ext_ids, s.t, latest,
                       s.dec_state)
        # candidate pool: every live hyp x its 2K continuations
        cand_lp = s.sum_lp[:, None] + step.topk_log_probs  # [K, 2K]
        # step 0: all hyps identical -> expand only hyp 0 (beam_search.py:125)
        first = jnp.arange(K)[:, None] == 0
        cand_lp = jnp.where(jnp.logical_or(s.t > 0, first), cand_lp, NEG)
        flat_lp = cand_lp.reshape(S)
        flat_tok = step.topk_ids.reshape(S)
        order = jnp.argsort(-flat_lp)  # stable descending
        srt_lp = flat_lp[order]
        srt_tok = flat_tok[order]
        parent = order // (2 * K)  # originating live hyp

        # sequential triage (beam_search.py:143-154) as cumsums: counts only
        # advance for selected candidates, and a candidate is processed only
        # while both pools are still short of K.
        is_stop = srt_tok == STOP_ID
        valid_stop = jnp.logical_and(is_stop, s.t >= hps.min_dec_steps)
        non_stop = jnp.logical_not(is_stop)
        live_rank = jnp.cumsum(non_stop)  # inclusive
        res_rank = jnp.cumsum(valid_stop)
        live_sel = non_stop & (live_rank <= K) & (s.n_res + res_rank < K)
        res_sel = valid_stop & (s.n_res + res_rank <= K) & (live_rank < K)

        # --- rebuild the live beam ---
        sel = jnp.argsort(jnp.logical_not(live_sel))[:K]  # first K selected
        ok = live_sel[sel]  # all True unless results filled first
        par = parent[sel]
        new_tokens = s.tokens[par].at[:, s.t + 1].set(srt_tok[sel])
        new_sum_lp = jnp.where(ok, srt_lp[sel], NEG)
        new_attn = s.attn_hist[par].at[:, s.t].set(step.attn_dist[par])
        new_pgen = s.pgen_hist[par].at[:, s.t].set(step.p_gen[par])

        # --- scatter finished hypotheses into result slots ---
        slot = jnp.where(res_sel, s.n_res + res_rank - 1, K)  # K = scratch
        cand_tokens = s.tokens[parent].at[:, s.t + 1].set(srt_tok)  # [S, T+1]
        cand_attn = s.attn_hist[parent].at[:, s.t].set(step.attn_dist[parent])
        cand_pgen = s.pgen_hist[parent].at[:, s.t].set(step.p_gen[parent])
        res_tokens = s.res_tokens.at[slot].set(cand_tokens)
        res_lp = s.res_lp.at[slot].set(jnp.where(res_sel, srt_lp, NEG))
        res_len = s.res_len.at[slot].set(s.t + 2)  # START + t+1 generated
        res_attn = s.res_attn.at[slot].set(cand_attn)
        res_pgen = s.res_pgen.at[slot].set(cand_pgen)
        # scratch row K may hold garbage; restore invariants there
        res_lp = res_lp.at[K].set(NEG)

        return _BeamState(
            t=s.t + 1,
            tokens=new_tokens,
            sum_lp=new_sum_lp,
            dec_state=jax.tree_util.tree_map(lambda x: x[par], step.state),
            attn_hist=new_attn,
            pgen_hist=new_pgen,
            n_res=s.n_res + jnp.sum(res_sel).astype(jnp.int32),
            res_tokens=res_tokens,
            res_lp=res_lp,
            res_len=res_len,
            res_attn=res_attn,
            res_pgen=res_pgen,
        )

    return body


def _masked_scan_body(cond, body):
    """Scan body with masked updates: once cond(s) goes false the state
    is carried through unchanged, so the result is token-exact with the
    while_loop (whose vmapped form does the same masking).  body's
    garbage reads past the horizon (OOB gathers clamp, OOB scatter
    writes drop) are discarded by the select."""

    def scan_body(s, _):
        s2 = body(s)
        keep = cond(s)
        s = jax.tree_util.tree_map(
            lambda old, new: jnp.where(keep, new, old), s, s2)
        return s, None

    return scan_body


def _search_one(params, hps: HParams, init_state_fn, step_fn, loop, chunk,
                enc_one, enc_mask, ext_ids) -> BeamSearchOutput:
    """Beam search for ONE article (un-batched inputs; vmapped below).

    enc_one: the family's per-article encoder view (pytree, no batch
    axis); enc_mask: [T_enc]; ext_ids: [T_enc] extended-vocab ids.
    init_state_fn/step_fn: the family's beam adapter (models/__init__).
    loop: 'while', 'scan', or 'chunked' (see _loop_kind); chunk: the
    chunked inner-scan length, or None for the TS_BEAM_CHUNK env default
    (read here, at trace time).
    """
    T = hps.max_dec_steps
    T_enc = enc_mask.shape[0]
    init = _init_beam_state(hps, T_enc, init_state_fn(params, enc_one))
    cond = _beam_cond(hps)
    body = _make_beam_body(params, hps, step_fn, enc_one, enc_mask, ext_ids)
    scan_body = _masked_scan_body(cond, body)

    if loop == "while":
        s = jax.lax.while_loop(cond, body, init)
    elif loop == "chunked":
        # while over fixed-size scan chunks: the RPC-proxied backend
        # charges ~1.4 ms per DYNAMIC loop iteration (host round trip on
        # the condition) but nothing per scan step, so ceil(T/C) dynamic
        # iterations buy while-style early exit (typical beams finish
        # well before max_dec_steps) at near-scan dispatch cost.  The
        # masked inner scan makes overshooting a chunk a no-op, so the
        # result stays token-exact with both other kinds.
        if chunk is None:  # env fallback, read at trace time
            chunk = resolved_chunk("chunked")
        C = min(max(int(chunk), 1), T)

        def chunk_body(s):
            s, _ = jax.lax.scan(scan_body, s, None, length=C)
            return s

        s = jax.lax.while_loop(cond, chunk_body, init)
    else:
        s, _ = jax.lax.scan(scan_body, init, None, length=T)

    return _finalize_beam(hps, s, T_enc)


def _finalize_beam(hps: HParams, s: _BeamState, T_enc: int,
                   ) -> BeamSearchOutput:
    """Rank the finished pool (falling back to the live beam) and emit
    the best hypothesis — the reference's post-loop selection
    (beam_search.py:158-168), shared by _search_one and unpack_slot_jit.
    """
    K = hps.beam_size
    T = hps.max_dec_steps
    # results empty -> fall back to the live beam (beam_search.py:158-160)
    use_live = s.n_res == 0
    live_len = s.t + 1  # START + t generated tokens
    pool_lp = jnp.where(use_live, jnp.concatenate([s.sum_lp, jnp.array([NEG])]),
                        s.res_lp)
    pool_len = jnp.where(use_live, jnp.full((K + 1,), live_len),
                         s.res_len)
    pool_tokens = jnp.where(use_live,
                            jnp.concatenate([s.tokens,
                                             jnp.zeros((1, T + 1), jnp.int32)]),
                            s.res_tokens)
    pool_attn = jnp.where(
        use_live,
        jnp.concatenate([s.attn_hist, jnp.zeros((1, T, T_enc))]), s.res_attn)
    pool_pgen = jnp.where(
        use_live, jnp.concatenate([s.pgen_hist, jnp.zeros((1, T))]), s.res_pgen)

    avg = pool_lp / pool_len.astype(jnp.float32)  # beam_search.py:77-79
    avg = jnp.where(pool_lp <= NEG / 2, NEG, avg)  # keep empty slots last
    best = jnp.argmax(avg)
    return BeamSearchOutput(tokens=pool_tokens[best],
                            length=pool_len[best],
                            avg_log_prob=avg[best],
                            attn_dists=pool_attn[best],
                            p_gens=pool_pgen[best])


def _search_batch(params, hps: HParams, arrays: Dict[str, Array],
                  loop: Optional[str] = None,
                  chunk: Optional[int] = None) -> BeamSearchOutput:
    """Encode a batch of B articles once, then vmap the per-article search.

    loop=None / chunk=None read TS_BEAM_LOOP / TS_BEAM_CHUNK at trace
    time (fine for callers that trace once, like the sharded step in
    parallel/mesh.py; jit callers that must react to env changes pass
    them explicitly — they are static cache-key arguments on
    run_beam_search_jit).
    """
    family = get_family(hps.model_family)
    enc_view = family.beam_encode(params, hps, arrays)
    init_state_fn, step_fn = family.beam_adapter(hps)
    fn = functools.partial(_search_one, params, hps, init_state_fn, step_fn,
                           _loop_kind(loop), chunk)
    return jax.vmap(fn)(enc_view, arrays["enc_padding_mask"],
                        arrays["enc_batch_extend_vocab"])


@functools.partial(jax.jit, static_argnames=("hps", "loop", "chunk"))
def run_beam_search_jit(params, hps: HParams, arrays: Dict[str, Array],
                        loop: Optional[str] = None,
                        chunk: Optional[int] = None) -> BeamSearchOutput:
    return _search_batch(params, hps, arrays, loop, chunk)


# --------------------------------------------------------------------------
# Slot-state search: the continuous-batching kernel set (ISSUE 6)
# --------------------------------------------------------------------------
#
# The batch search above is all-or-nothing: one dispatch decodes B
# articles and returns when the SLOWEST finishes — the straggler barrier
# FastSeq (PAPERS.md) removes.  The slot API splits that dispatch into
# chunk-granular pieces over a persistent [slots, beam, ...] state so a
# host scheduler (serve/batcher.ContinuousBatcher) can retire finished
# articles and refill their slots between chunks:
#
#     state = init_slots_jit(params, hps, zero_arrays)     # once
#     state = pack_slot_jit(params, hps, state, i, arrays1) # admit
#     state, finished = step_slots_jit(params, hps, state, active, chunk)
#     out = unpack_slot_jit(hps, state, i)                  # retire
#
# Contracts:
#   * every kernel is shape-stable — slot index and active mask are
#     TRACED arguments, so after the four warmup compiles NO request,
#     slot choice, or occupancy pattern triggers a recompile;
#   * per-slot activity masks: an inactive slot's state is carried
#     through step_slots_jit unchanged (same masked-update select as the
#     'chunked' batch loop, so a resident article's trajectory is
#     token-exact with _search_one on the same inputs);
#   * pack/unpack happen ONLY at chunk boundaries — the host never
#     observes (or mutates) mid-chunk state.
#
# The per-article search itself is the SAME _make_beam_body /
# _init_beam_state / _finalize_beam code the batch path runs; the slot
# layer adds routing, not semantics.


class SlotState(NamedTuple):
    """Persistent decode state for `slots` resident articles.

    beam leaves lead with [slots, ...] (each slot an independent
    _BeamState); enc_view is the family's per-article encoder pytree
    stacked over slots; enc_mask/ext_ids are [slots, T_enc].  All
    shapes static: T_enc is fixed for the state's lifetime (continuous
    serving pads every article to one length instead of bucketing —
    one resident shape is what makes slot recycling shape-stable).
    """

    beam: Any  # _BeamState with [slots, ...] leaves
    enc_view: Any  # family encoder view, [slots, ...] leaves
    enc_mask: Array  # [slots, T_enc]
    ext_ids: Array  # [slots, T_enc]


def _init_slot_beams(params, hps: HParams, enc_view, enc_mask):
    """vmapped step-0 beam state for a stack of articles."""
    family = get_family(hps.model_family)
    init_state_fn, _ = family.beam_adapter(hps)

    def one(enc_one, mask):
        return _init_beam_state(hps, mask.shape[0],
                                init_state_fn(params, enc_one))

    return jax.vmap(one)(enc_view, enc_mask)


@functools.partial(jax.jit, static_argnames=("hps",))
def init_slots_jit(params, hps: HParams,
                   arrays: Dict[str, Array]) -> SlotState:
    """The all-empty persistent state from a [slots, T_enc] arrays dict
    (zeros are fine: inactive slots are never stepped unmasked and are
    fully overwritten by pack_slot_jit before first use)."""
    family = get_family(hps.model_family)
    enc_view = family.beam_encode(params, hps, arrays)
    return SlotState(
        beam=_init_slot_beams(params, hps, enc_view,
                              arrays["enc_padding_mask"]),
        enc_view=enc_view,
        enc_mask=arrays["enc_padding_mask"],
        ext_ids=arrays["enc_batch_extend_vocab"])


@functools.partial(jax.jit, static_argnames=("hps",))
def pack_slot_jit(params, hps: HParams, state: SlotState, idx,
                  arrays: Dict[str, Array]) -> SlotState:
    """Admit ONE article (leading axis 1) into slot `idx`: encode it,
    initialize its search, and scatter both into the persistent state.
    `idx` is traced — one compile serves every slot."""
    family = get_family(hps.model_family)
    enc_view1 = family.beam_encode(params, hps, arrays)
    beam1 = _init_slot_beams(params, hps, enc_view1,
                             arrays["enc_padding_mask"])

    def write(dst, src):
        return dst.at[idx].set(src[0])

    return SlotState(
        beam=jax.tree_util.tree_map(write, state.beam, beam1),
        enc_view=jax.tree_util.tree_map(write, state.enc_view, enc_view1),
        enc_mask=state.enc_mask.at[idx].set(arrays["enc_padding_mask"][0]),
        ext_ids=state.ext_ids.at[idx].set(
            arrays["enc_batch_extend_vocab"][0]))


@functools.partial(jax.jit, static_argnames=("hps", "chunk"))
def step_slots_jit(params, hps: HParams, state: SlotState, active,
                   chunk: int):
    """Advance every ACTIVE slot by up to `chunk` masked decode steps.

    active: [slots] bool.  Returns (state', finished) where finished[i]
    marks an active slot whose search is done (horizon reached or beam
    full of results) — the host retires it via unpack_slot_jit and may
    refill.  Inactive slots run the same chunk on garbage state but
    every update is discarded by the mask (the cost of shape stability;
    a NaN in a dead lane never escapes the select)."""
    family = get_family(hps.model_family)
    _, step_fn = family.beam_adapter(hps)
    cond = _beam_cond(hps)

    def one(beam, act, enc_one, mask, ext):
        body = _make_beam_body(params, hps, step_fn, enc_one, mask, ext)

        def masked_cond(s):
            return jnp.logical_and(act, cond(s))

        scan_body = _masked_scan_body(masked_cond, body)
        s, _ = jax.lax.scan(scan_body, beam, None, length=chunk)
        return s, jnp.logical_and(act, jnp.logical_not(cond(s)))

    beam, finished = jax.vmap(one)(state.beam, active, state.enc_view,
                                   state.enc_mask, state.ext_ids)
    return state._replace(beam=beam), finished


@functools.partial(jax.jit, static_argnames=("hps",))
def unpack_slot_jit(hps: HParams, state: SlotState, idx) -> BeamSearchOutput:
    """The finished hypothesis for slot `idx` (no batch axis), ranked
    exactly like the batch path's tail.  `idx` is traced — one compile.
    The slot is NOT cleared here; the host's activity mask retires it
    and the next pack overwrites the state."""
    s = jax.tree_util.tree_map(lambda x: x[idx], state.beam)
    return _finalize_beam(hps, s, state.enc_mask.shape[1])


def resolved_chunk(loop: str) -> Optional[int]:
    """The effective chunked inner-scan length, resolved from the env —
    pass this to run_beam_search_jit so the chunk size participates in
    the jit cache key (an env change between calls would otherwise be
    silently ignored by the cached executable).  The default lives in
    config.beam_chunk_from_env (single source, shared with bench.py's
    config fingerprint)."""
    if loop != "chunked":
        return None
    from textsummarization_on_flink_tpu.config import beam_chunk_from_env

    return beam_chunk_from_env()


def run_beam_search(params, hps: HParams, arrays: Dict[str, np.ndarray],
                    ) -> BeamSearchOutput:
    """Host entry: one compiled dispatch decodes the whole batch.

    Returns host numpy BeamSearchOutput; callers strip START/[STOP] and map
    ids back to words (decode/decoder.py, mirroring decode.py:109-119).
    """
    loop = _loop_kind()
    try:  # jit-cache growth across this call = a fresh trace/compile
        before = run_beam_search_jit._cache_size()
    except Exception:  # tslint: disable=TS005 — _cache_size is a private jax API; telemetry must never break decode
        before = None
    out = run_beam_search_jit(params, hps, arrays, loop=loop,
                              chunk=resolved_chunk(loop))
    if before is not None:
        try:
            from textsummarization_on_flink_tpu import obs

            missed = run_beam_search_jit._cache_size() > before
            obs.registry_for(hps).counter(
                "decode/compile_cache_misses_total" if missed
                else "decode/compile_cache_hits_total").inc()
        except Exception:  # tslint: disable=TS005 — best-effort cache-hit telemetry; decode result already in hand
            pass
    return BeamSearchOutput(*[np.asarray(x) for x in out])
