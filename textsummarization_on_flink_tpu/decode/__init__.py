from textsummarization_on_flink_tpu.decode import beam_search  # noqa: F401
from textsummarization_on_flink_tpu.decode import decoder  # noqa: F401
from textsummarization_on_flink_tpu.decode import speculative  # noqa: F401
