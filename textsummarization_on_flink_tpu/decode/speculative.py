"""Speculative decode: draft-then-verify fast path (ISSUE 10 tentpole).

A cheap DRAFT model (the avg_attention family, whose decode step is O(1)
in history) proposes ``spec_k`` tokens greedily; the FULL model scores
all ``spec_k + 1`` positions in ONE teacher-forced batched step and the
longest draft prefix agreeing with the full model's own greedy choices
is accepted, plus the full model's correction token at the first
disagreement — so every cycle emits at least one token and the emitted
stream is **token-exact with full-model greedy decode by construction**:
each emitted token is either the full model's argmax at its position
(the correction) or a draft token that EQUALS the full model's argmax
there (the acceptance test).  "Greedy decode" here is exactly the
serving ladder's greedy tier — ``beam_size=1`` beam search, whose
candidate triage degenerates to argmax with the same
discard-early-STOP policy ``_greedy_choice`` implements (pinned by the
tier-1 exactness tests for both families).

The whole per-article search — draft proposal, verify, acceptance,
commit — runs inside one jitted ``lax.while_loop`` with the accept
length TRACED (the same compile discipline as ``step_slots_jit``):
after the one warmup compile, NO acceptance pattern, article content,
or draft quality triggers a recompile (pinned by test).

Verify paths per full-model family:

  * transformer — ``transformer.spec_verify``: one PARALLEL decoder
    pass scores all spec_k+1 positions against the incremental KV
    cache (the "fewer, fatter steps" restructuring FastSeq-style
    serving wins come from, PAPERS.md): the expensive model streams its
    weights once per CYCLE instead of once per token, which on a
    bandwidth-bound decode step is the speedup lever the
    BYTE_BUDGET.json ``spec`` gate models.  The cache is append-only;
    acceptance never rolls it back — the committed step counter masks
    rejected positions and the next block overwrites them.
  * any other family (LSTM pointer-generator, avg_attention) — a
    teacher-forced ``lax.scan`` of the family's OWN beam-adapter step
    (K=1): still one dispatch per cycle, bitwise the greedy step (an
    LSTM's state is inherently sequential, so there is no parallel
    form; the win is dispatch restructuring, not FLOPs — stated in
    PERF.md).

Draft proposal runs ``spec_k + 1`` draft steps per cycle (one extra so
the accept-all case's resync state exists without a traced branch);
after acceptance the draft state re-anchors to the stacked proposal
state at the emitted length and the correction token becomes the next
cycle's first input.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from textsummarization_on_flink_tpu.config import HParams, derive_draft_hps
from textsummarization_on_flink_tpu.data.vocab import START_ID, STOP_ID, UNK_ID
from textsummarization_on_flink_tpu.decode.beam_search import NEG
from textsummarization_on_flink_tpu.models import get_family

Array = jax.Array


class SpecDecodeOutput(NamedTuple):
    """Batch output: the BeamSearchOutput field set (so the decoder's
    ``_make_result`` consumes it unchanged) plus per-article speculative
    telemetry."""

    tokens: Array  # [B, T_dec+1] extended-vocab ids, [0]=START
    length: Array  # [B] token count including START
    avg_log_prob: Array  # [B]
    attn_dists: Array  # [B, T_dec, T_enc]
    p_gens: Array  # [B, T_dec]
    cycles: Array  # [B] draft-verify rounds run
    drafted: Array  # [B] draft tokens proposed (cycles * spec_k)
    accepted: Array  # [B] draft tokens accepted by the verifier
    accept_hist: Array  # [B, spec_k+1] count of cycles per accept length


class _SpecCarry(NamedTuple):
    """Per-article loop state.  ``tokens``/``attn``/``pgens`` carry a
    scratch row at index T that truncated writes land in (same trick as
    the beam search's scratch column)."""

    t: Array  # scalar int32: committed generated-token count
    last: Array  # scalar int32: last committed token (raw extended id)
    done: Array  # scalar bool
    sum_lp: Array  # scalar f32: sum of committed tokens' log probs
    tokens: Array  # [T+1] int32
    attn: Array  # [T+1, T_enc] f32
    pgens: Array  # [T+1] f32
    f_state: Any  # full-model verify state
    d_state: Any  # draft-model adapter state (K=1 leaves)
    cycles: Array  # scalar int32
    accepted: Array  # scalar int32
    hist: Array  # [spec_k+1] int32


def _greedy_choice(topk_ids: Array, topk_lps: Array, t: Array,
                   min_dec_steps: int):
    """The greedy policy shared by draft proposal and verify: argmax
    with STOP discarded before ``min_dec_steps`` — exactly the
    ``beam_size=1`` triage (an early STOP candidate is dropped and the
    next-best continuation survives, beam_search.py:143-154), so greedy
    == beam-1 token for token.  ``topk_*`` are ONE position's top-2
    (descending); returns (token, its log prob)."""
    blocked = jnp.logical_and(topk_ids == STOP_ID, t < min_dec_steps)
    idx = jnp.argmax(jnp.where(blocked, NEG, topk_lps))
    return topk_ids[idx], topk_lps[idx]


def _map_unk(tokens: Array, vocab_size: int) -> Array:
    """Extended-vocab ids feed back as UNK (beam_search.py:112)."""
    return jnp.where(tokens >= vocab_size, UNK_ID, tokens)


def _make_full_driver(params, hps: HParams, spec_k: int, enc_one,
                      enc_mask, ext_ids):
    """(init_state, verify, commit) for the FULL model.

    verify(state, t0, inputs[S]) -> (choices [S], lps [S],
    attn [S, T_enc], pgen [S], aux); commit(aux, a) -> the state
    consistent with the prefix extended by the first a+1 inputs.
    """
    S = spec_k + 1
    choose = jax.vmap(_greedy_choice, in_axes=(0, 0, 0, None))

    if hps.model_family == "transformer":
        family = get_family(hps.model_family)

        def init_state():
            return family.spec_init_state(hps, spec_k)

        def verify(state, t0, inputs):
            tids, tlps, attn, pgen, new_state = family.spec_verify(
                params, hps, enc_one, enc_mask, ext_ids, t0,
                _map_unk(inputs, hps.vocab_size), state)
            toks, lps = choose(tids, tlps, t0 + jnp.arange(S),
                               hps.min_dec_steps)
            return toks, lps, attn, pgen, new_state

        def commit(aux, a):
            del a  # append-only cache: validity rides the step counter
            return aux

        return init_state, verify, commit

    family = get_family(hps.model_family)
    init_fn, step_fn = family.beam_adapter(hps)

    def init_state():
        return init_fn(params, enc_one)

    def verify(state, t0, inputs):
        def body(st, j_inp):
            j, inp = j_inp
            latest = _map_unk(inp, hps.vocab_size)[None]
            out = step_fn(params, enc_one, enc_mask, ext_ids, t0 + j,
                          latest, st)
            return out.state, (out.topk_ids[0], out.topk_log_probs[0],
                               out.attn_dist[0], out.p_gen[0], out.state)

        _, (tids, tlps, attn, pgen, states) = jax.lax.scan(
            body, state, (jnp.arange(S), inputs))
        toks, lps = choose(tids, tlps, t0 + jnp.arange(S),
                           hps.min_dec_steps)
        return toks, lps, attn, pgen, states

    def commit(aux, a):
        # stacked[j] = state after consuming inputs 0..j; accepting a
        # draft tokens means the prefix grew by inputs 0..a
        return jax.tree_util.tree_map(lambda x: x[a], aux)

    return init_state, verify, commit


def _spec_body(draft_params, fhps: HParams, spec_k: int, d_enc_one,
               enc_mask, ext_ids, verify, commit, d_step):
    """One draft-propose / verify / accept / commit cycle for one
    article — the loop body `_spec_one` runs under lax.while_loop.
    The full model arrives entirely through the `verify`/`commit`
    closures (already closed over params and encoder view); only the
    draft's step still needs its raw operands here.  Factored out so
    the tslint hot list can name it (TS002)."""
    T = fhps.max_dec_steps
    V = fhps.vocab_size
    S = spec_k + 1

    def body(c: _SpecCarry) -> _SpecCarry:
        # --- draft proposes spec_k tokens greedily (S = spec_k+1 steps:
        # the extra step computes the accept-all resync state) ---
        def d_body(dc, j):
            st, latest = dc
            out = d_step(draft_params, d_enc_one, enc_mask, ext_ids,
                         c.t + j, latest[None], st)
            tok, _ = _greedy_choice(out.topk_ids[0], out.topk_log_probs[0],
                                    c.t + j, fhps.min_dec_steps)
            return (out.state, _map_unk(tok, V)), (tok, out.state)

        (_, _), (d_toks, d_states) = jax.lax.scan(
            d_body, (c.d_state, _map_unk(c.last, V)), jnp.arange(S))
        # d_toks[j] = the draft's proposal for position t+j+1

        # --- full model scores all S positions in one batched step ---
        inputs = jnp.concatenate([c.last[None], d_toks[:spec_k]])
        g_toks, g_lps, v_attn, v_pgen, v_aux = verify(c.f_state, c.t,
                                                      inputs)

        # --- longest agreeing prefix + correction (traced length) ---
        agree = (d_toks[:spec_k] == g_toks[:spec_k]).astype(jnp.int32)
        a = jnp.sum(jnp.cumprod(agree))  # 0..spec_k leading agreements
        e = jnp.where(jnp.arange(S) < a, d_toks, g_toks)  # emitted run
        within = jnp.arange(S) <= a
        is_stop = jnp.logical_and(e == STOP_ID, within)
        any_stop = jnp.any(is_stop)
        first_stop = jnp.argmax(is_stop)
        n_limit = jnp.where(any_stop, first_stop + 1, a + 1)
        n = jnp.minimum(n_limit, T - c.t)  # >= 1: loop only runs t < T
        valid = jnp.arange(S) < n

        # --- commit: scatter the n emitted tokens (scratch row T
        # absorbs the truncated tail) and advance both models ---
        widx = jnp.where(valid, c.t + jnp.arange(S), T)
        stopped = jnp.logical_and(any_stop, first_stop < n)
        t2 = c.t + n
        return _SpecCarry(
            t=t2,
            last=e[n - 1],
            done=jnp.logical_or(stopped, t2 >= T),
            sum_lp=c.sum_lp + jnp.sum(jnp.where(valid, g_lps, 0.0)),
            tokens=c.tokens.at[widx].set(e),
            attn=c.attn.at[widx].set(v_attn),
            pgens=c.pgens.at[widx].set(v_pgen),
            f_state=commit(v_aux, a),
            d_state=jax.tree_util.tree_map(lambda x: x[n - 1], d_states),
            cycles=c.cycles + 1,
            accepted=c.accepted + a,
            hist=c.hist.at[a].add(1),
        )

    return body


def _spec_one(full_params, draft_params, fhps: HParams, dhps: HParams,
              spec_k: int, f_enc_one, d_enc_one, enc_mask, ext_ids):
    """Speculative decode for ONE article (vmapped over the batch).
    fhps/dhps arrive with beam_size=1 — run_spec_decode, the one host
    entry, normalizes them so the jit cache key cannot fragment over a
    beam width the engine ignores."""
    T = fhps.max_dec_steps
    T_enc = enc_mask.shape[0]
    f_init, verify, commit = _make_full_driver(
        full_params, fhps, spec_k, f_enc_one, enc_mask, ext_ids)
    d_init_fn, d_step = get_family(dhps.model_family).beam_adapter(dhps)
    body = _spec_body(draft_params, fhps, spec_k, d_enc_one, enc_mask,
                      ext_ids, verify, commit, d_step)
    init = _SpecCarry(
        t=jnp.zeros((), jnp.int32),
        last=jnp.asarray(START_ID, jnp.int32),
        done=jnp.zeros((), jnp.bool_),
        sum_lp=jnp.zeros((), jnp.float32),
        tokens=jnp.zeros((T + 1,), jnp.int32),
        attn=jnp.zeros((T + 1, T_enc), jnp.float32),
        pgens=jnp.zeros((T + 1,), jnp.float32),
        f_state=f_init(),
        d_state=d_init_fn(draft_params, d_enc_one),
        cycles=jnp.zeros((), jnp.int32),
        accepted=jnp.zeros((), jnp.int32),
        hist=jnp.zeros((spec_k + 1,), jnp.int32),
    )
    c = jax.lax.while_loop(lambda s: jnp.logical_not(s.done), body, init)
    length = c.t + 1  # generated tokens + START (the beam length rule)
    return SpecDecodeOutput(
        tokens=jnp.concatenate([jnp.array([START_ID], jnp.int32),
                                c.tokens[:T]]),
        length=length,
        avg_log_prob=c.sum_lp / length.astype(jnp.float32),
        attn_dists=c.attn[:T],
        p_gens=c.pgens[:T],
        cycles=c.cycles,
        drafted=c.cycles * spec_k,
        accepted=c.accepted,
        accept_hist=c.hist,
    )


@functools.partial(jax.jit, static_argnames=("fhps", "dhps", "spec_k"))
def run_spec_decode_jit(full_params, draft_params, fhps: HParams,
                        dhps: HParams, arrays: Dict[str, Array],
                        spec_k: int) -> SpecDecodeOutput:
    """One compiled dispatch speculatively decodes the whole batch.
    Both models encode the article batch once; the per-article loop is
    vmapped.  Everything downstream of the encoders is shape-static —
    accept length, cycle count, and slot content are all traced.
    fhps/dhps must carry beam_size=1 (the engine is single-hypothesis;
    ``run_spec_decode`` normalizes so differing beam widths cannot
    fragment the jit cache)."""
    f_family = get_family(fhps.model_family)
    d_family = get_family(dhps.model_family)
    f_enc = f_family.beam_encode(full_params, fhps, arrays)
    d_enc = d_family.beam_encode(draft_params, dhps, arrays)
    fn = functools.partial(_spec_one, full_params, draft_params, fhps,
                           dhps, spec_k)
    return jax.vmap(fn)(f_enc, d_enc, arrays["enc_padding_mask"],
                        arrays["enc_batch_extend_vocab"])


def run_spec_decode(full_params, draft_params, hps: HParams,
                    arrays: Dict[str, np.ndarray]) -> SpecDecodeOutput:
    """Host entry: resolve the draft shape (config.derive_draft_hps),
    dispatch once, return host numpy (run_beam_search's contract, plus
    the speculative stats)."""
    fhps = hps.replace(beam_size=1)  # the verify path is single-hyp
    dhps = derive_draft_hps(hps).replace(beam_size=1, mode="decode")
    enc_arrays = {k: v for k, v in arrays.items() if k.startswith("enc_")}
    try:  # mirror run_beam_search's compile-cache telemetry
        before = run_spec_decode_jit._cache_size()
    except Exception:  # tslint: disable=TS005 — private jax API; telemetry must never break decode
        before = None
    out = run_spec_decode_jit(full_params, draft_params, fhps, dhps,
                              enc_arrays, int(hps.spec_k))
    if before is not None:
        try:
            from textsummarization_on_flink_tpu import obs

            missed = run_spec_decode_jit._cache_size() > before
            obs.registry_for(hps).counter(
                "decode/compile_cache_misses_total" if missed
                else "decode/compile_cache_hits_total").inc()
        except Exception:  # tslint: disable=TS005 — best-effort cache-hit telemetry; decode result already in hand
            pass
    return SpecDecodeOutput(*[np.asarray(x) for x in out])


def expected_speedup(alpha: float, spec_k: int, draft_ratio: float) -> float:
    """Expected spec-tier speedup over plain greedy under the
    bandwidth-bound decode model (PERF.md "Speculative tier"): with
    per-position acceptance probability ``alpha``, a cycle emits
    E = (1 - alpha^(k+1)) / (1 - alpha) tokens in expectation and costs
    (k+1) draft steps (the +1 is the resync step) plus ONE full-model
    invocation — the parallel verify streams the full model's weights
    once for all k+1 positions, which is what makes a verify invocation
    ~one full step on a bandwidth-bound decoder.  ``draft_ratio`` is
    the committed draft/full per-step cost ratio (BYTE_BUDGET.json
    "spec").  Greedy costs 1 full step per token, so speedup =
    E / ((k+1) * ratio + 1)."""
    a = min(max(float(alpha), 0.0), 1.0)
    if a >= 1.0:
        e = float(spec_k + 1)
    else:
        e = (1.0 - a ** (spec_k + 1)) / (1.0 - a)
    return e / ((spec_k + 1) * float(draft_ratio) + 1.0)
