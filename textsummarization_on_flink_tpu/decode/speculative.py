"""Speculative decode: draft-then-verify fast path (ISSUE 10 tentpole).

A cheap DRAFT model (the avg_attention family, whose decode step is O(1)
in history) proposes ``spec_k`` tokens greedily; the FULL model scores
all ``spec_k + 1`` positions in ONE teacher-forced batched step and the
longest draft prefix agreeing with the full model's own greedy choices
is accepted, plus the full model's correction token at the first
disagreement — so every cycle emits at least one token and the emitted
stream is **token-exact with full-model greedy decode by construction**:
each emitted token is either the full model's argmax at its position
(the correction) or a draft token that EQUALS the full model's argmax
there (the acceptance test).  "Greedy decode" here is exactly the
serving ladder's greedy tier — ``beam_size=1`` beam search, whose
candidate triage degenerates to argmax with the same
discard-early-STOP policy ``_greedy_choice`` implements (pinned by the
tier-1 exactness tests for both families).

The whole per-article search — draft proposal, verify, acceptance,
commit — runs inside one jitted ``lax.while_loop`` with the accept
length TRACED (the same compile discipline as ``step_slots_jit``):
after the one warmup compile, NO acceptance pattern, article content,
or draft quality triggers a recompile (pinned by test).

Verify paths per full-model family:

  * transformer — ``transformer.spec_verify``: one PARALLEL decoder
    pass scores all spec_k+1 positions against the incremental KV
    cache (the "fewer, fatter steps" restructuring FastSeq-style
    serving wins come from, PAPERS.md): the expensive model streams its
    weights once per CYCLE instead of once per token, which on a
    bandwidth-bound decode step is the speedup lever the
    BYTE_BUDGET.json ``spec`` gate models.  The cache is append-only;
    acceptance never rolls it back — the committed step counter masks
    rejected positions and the next block overwrites them.
  * any other family (LSTM pointer-generator, avg_attention) — a
    teacher-forced ``lax.scan`` of the family's OWN beam-adapter step
    (K=1): still one dispatch per cycle, bitwise the greedy step (an
    LSTM's state is inherently sequential, so there is no parallel
    form; the win is dispatch restructuring, not FLOPs — stated in
    PERF.md).

Draft proposal runs ``spec_k + 1`` draft steps per cycle (one extra so
the accept-all case's resync state exists without a traced branch);
after acceptance the draft state re-anchors to the stacked proposal
state at the emitted length and the correction token becomes the next
cycle's first input.

Acceptance-adaptive spec_k (ISSUE 12): ``hps.spec_k_adaptive`` swaps
the one-dispatch while_loop for a HOST-stepped cycle loop — one jitted
batch dispatch per draft-verify cycle (``spec_cycle_jit``) — so the
``SpecKController`` can re-pick k between cycles from the measured
accept histogram via the expected-progress-per-FLOP model
(``expected_speedup`` at the committed BYTE_BUDGET.json draft/full
ratio).  The carry's shapes are pinned to ``spec_k_max`` (verify cache
width, histogram rows), so each distinct k in the warm set costs
exactly ONE compile and the warm set is bounded by the committed
[spec_k_min, spec_k_max] range (pinned by test).  Token exactness is
k-independent: every cycle still emits the longest draft prefix that
matches the unchanged verifier's own greedy choices, so ANY k sequence
reproduces full-model greedy exactly.
"""

from __future__ import annotations

import functools
import json
import os
from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from textsummarization_on_flink_tpu.config import HParams, derive_draft_hps
from textsummarization_on_flink_tpu.data.vocab import START_ID, STOP_ID, UNK_ID
from textsummarization_on_flink_tpu.decode.beam_search import NEG
from textsummarization_on_flink_tpu.models import get_family

Array = jax.Array


class SpecDecodeOutput(NamedTuple):
    """Batch output: the BeamSearchOutput field set (so the decoder's
    ``_make_result`` consumes it unchanged) plus per-article speculative
    telemetry."""

    tokens: Array  # [B, T_dec+1] extended-vocab ids, [0]=START
    length: Array  # [B] token count including START
    avg_log_prob: Array  # [B]
    attn_dists: Array  # [B, T_dec, T_enc]
    p_gens: Array  # [B, T_dec]
    cycles: Array  # [B] draft-verify rounds run
    drafted: Array  # [B] draft tokens proposed (cycles * spec_k)
    accepted: Array  # [B] draft tokens accepted by the verifier
    accept_hist: Array  # [B, spec_k+1] count of cycles per accept length


class _SpecCarry(NamedTuple):
    """Per-article loop state.  ``tokens``/``attn``/``pgens`` carry a
    scratch row at index T that truncated writes land in (same trick as
    the beam search's scratch column)."""

    t: Array  # scalar int32: committed generated-token count
    last: Array  # scalar int32: last committed token (raw extended id)
    done: Array  # scalar bool
    sum_lp: Array  # scalar f32: sum of committed tokens' log probs
    tokens: Array  # [T+1] int32
    attn: Array  # [T+1, T_enc] f32
    pgens: Array  # [T+1] f32
    f_state: Any  # full-model verify state
    d_state: Any  # draft-model adapter state (K=1 leaves)
    cycles: Array  # scalar int32
    accepted: Array  # scalar int32
    hist: Array  # [k_cap+1] int32 (k_cap = spec_k, or spec_k_max adaptive)
    drafted: Array  # scalar int32: draft tokens proposed (sum of per-cycle k)


def _greedy_choice(topk_ids: Array, topk_lps: Array, t: Array,
                   min_dec_steps: int):
    """The greedy policy shared by draft proposal and verify: argmax
    with STOP discarded before ``min_dec_steps`` — exactly the
    ``beam_size=1`` triage (an early STOP candidate is dropped and the
    next-best continuation survives, beam_search.py:143-154), so greedy
    == beam-1 token for token.  ``topk_*`` are ONE position's top-2
    (descending); returns (token, its log prob)."""
    blocked = jnp.logical_and(topk_ids == STOP_ID, t < min_dec_steps)
    idx = jnp.argmax(jnp.where(blocked, NEG, topk_lps))
    return topk_ids[idx], topk_lps[idx]


def _map_unk(tokens: Array, vocab_size: int) -> Array:
    """Extended-vocab ids feed back as UNK (beam_search.py:112)."""
    return jnp.where(tokens >= vocab_size, UNK_ID, tokens)


def _make_full_driver(params, hps: HParams, spec_k: int, enc_one,
                      enc_mask, ext_ids, cache_k: int = None):
    """(init_state, verify, commit) for the FULL model.

    verify(state, t0, inputs[S]) -> (choices [S], lps [S],
    attn [S, T_enc], pgen [S], aux); commit(aux, a) -> the state
    consistent with the prefix extended by the first a+1 inputs.
    ``cache_k`` sizes the verify cache independently of the cycle's
    spec_k (the adaptive engine pins it to spec_k_max so every k in
    the warm set shares ONE carry shape); None = spec_k.
    """
    S = spec_k + 1
    cache_k = spec_k if cache_k is None else cache_k
    choose = jax.vmap(_greedy_choice, in_axes=(0, 0, 0, None))

    if hps.model_family == "transformer":
        family = get_family(hps.model_family)

        def init_state():
            return family.spec_init_state(hps, cache_k)

        def verify(state, t0, inputs):
            tids, tlps, attn, pgen, new_state = family.spec_verify(
                params, hps, enc_one, enc_mask, ext_ids, t0,
                _map_unk(inputs, hps.vocab_size), state)
            toks, lps = choose(tids, tlps, t0 + jnp.arange(S),
                               hps.min_dec_steps)
            return toks, lps, attn, pgen, new_state

        def commit(aux, a):
            del a  # append-only cache: validity rides the step counter
            return aux

        return init_state, verify, commit

    family = get_family(hps.model_family)
    init_fn, step_fn = family.beam_adapter(hps)

    def init_state():
        return init_fn(params, enc_one)

    def verify(state, t0, inputs):
        def body(st, j_inp):
            j, inp = j_inp
            latest = _map_unk(inp, hps.vocab_size)[None]
            out = step_fn(params, enc_one, enc_mask, ext_ids, t0 + j,
                          latest, st)
            return out.state, (out.topk_ids[0], out.topk_log_probs[0],
                               out.attn_dist[0], out.p_gen[0], out.state)

        _, (tids, tlps, attn, pgen, states) = jax.lax.scan(
            body, state, (jnp.arange(S), inputs))
        toks, lps = choose(tids, tlps, t0 + jnp.arange(S),
                           hps.min_dec_steps)
        return toks, lps, attn, pgen, states

    def commit(aux, a):
        # stacked[j] = state after consuming inputs 0..j; accepting a
        # draft tokens means the prefix grew by inputs 0..a
        return jax.tree_util.tree_map(lambda x: x[a], aux)

    return init_state, verify, commit


def _spec_body(draft_params, fhps: HParams, spec_k: int, d_enc_one,
               enc_mask, ext_ids, verify, commit, d_step):
    """One draft-propose / verify / accept / commit cycle for one
    article — the loop body `_spec_one` runs under lax.while_loop.
    The full model arrives entirely through the `verify`/`commit`
    closures (already closed over params and encoder view); only the
    draft's step still needs its raw operands here.  Factored out so
    the tslint hot list can name it (TS002)."""
    T = fhps.max_dec_steps
    V = fhps.vocab_size
    S = spec_k + 1

    def body(c: _SpecCarry) -> _SpecCarry:
        # --- draft proposes spec_k tokens greedily (S = spec_k+1 steps:
        # the extra step computes the accept-all resync state) ---
        def d_body(dc, j):
            st, latest = dc
            out = d_step(draft_params, d_enc_one, enc_mask, ext_ids,
                         c.t + j, latest[None], st)
            tok, _ = _greedy_choice(out.topk_ids[0], out.topk_log_probs[0],
                                    c.t + j, fhps.min_dec_steps)
            return (out.state, _map_unk(tok, V)), (tok, out.state)

        (_, _), (d_toks, d_states) = jax.lax.scan(
            d_body, (c.d_state, _map_unk(c.last, V)), jnp.arange(S))
        # d_toks[j] = the draft's proposal for position t+j+1

        # --- full model scores all S positions in one batched step ---
        inputs = jnp.concatenate([c.last[None], d_toks[:spec_k]])
        g_toks, g_lps, v_attn, v_pgen, v_aux = verify(c.f_state, c.t,
                                                      inputs)

        # --- longest agreeing prefix + correction (traced length) ---
        agree = (d_toks[:spec_k] == g_toks[:spec_k]).astype(jnp.int32)
        a = jnp.sum(jnp.cumprod(agree))  # 0..spec_k leading agreements
        e = jnp.where(jnp.arange(S) < a, d_toks, g_toks)  # emitted run
        within = jnp.arange(S) <= a
        is_stop = jnp.logical_and(e == STOP_ID, within)
        any_stop = jnp.any(is_stop)
        first_stop = jnp.argmax(is_stop)
        n_limit = jnp.where(any_stop, first_stop + 1, a + 1)
        n = jnp.minimum(n_limit, T - c.t)  # >= 1: loop only runs t < T
        valid = jnp.arange(S) < n

        # --- commit: scatter the n emitted tokens (scratch row T
        # absorbs the truncated tail) and advance both models ---
        widx = jnp.where(valid, c.t + jnp.arange(S), T)
        stopped = jnp.logical_and(any_stop, first_stop < n)
        t2 = c.t + n
        return _SpecCarry(
            t=t2,
            last=e[n - 1],
            done=jnp.logical_or(stopped, t2 >= T),
            sum_lp=c.sum_lp + jnp.sum(jnp.where(valid, g_lps, 0.0)),
            tokens=c.tokens.at[widx].set(e),
            attn=c.attn.at[widx].set(v_attn),
            pgens=c.pgens.at[widx].set(v_pgen),
            f_state=commit(v_aux, a),
            d_state=jax.tree_util.tree_map(lambda x: x[n - 1], d_states),
            cycles=c.cycles + 1,
            accepted=c.accepted + a,
            hist=c.hist.at[a].add(1),
            drafted=c.drafted + spec_k,
        )

    return body


def _article_fns(full_params, draft_params, fhps: HParams, dhps: HParams,
                 spec_k: int, k_cap: int):
    """(init_one, cycle_one) closures for ONE article — the shared
    engine core: the one-dispatch while_loop path composes them inside
    one trace, the adaptive path dispatches cycle_one per host cycle.
    ``k_cap`` pins the carry's k-dependent shapes (verify cache width,
    histogram rows) so cycles at different k share one carry."""
    T = fhps.max_dec_steps

    def init_one(f_enc_one, d_enc_one, enc_mask, ext_ids) -> _SpecCarry:
        T_enc = enc_mask.shape[0]
        f_init, _, _ = _make_full_driver(
            full_params, fhps, spec_k, f_enc_one, enc_mask, ext_ids,
            cache_k=k_cap)
        d_init_fn, _ = get_family(dhps.model_family).beam_adapter(dhps)
        return _SpecCarry(
            t=jnp.zeros((), jnp.int32),
            last=jnp.asarray(START_ID, jnp.int32),
            done=jnp.zeros((), jnp.bool_),
            sum_lp=jnp.zeros((), jnp.float32),
            tokens=jnp.zeros((T + 1,), jnp.int32),
            attn=jnp.zeros((T + 1, T_enc), jnp.float32),
            pgens=jnp.zeros((T + 1,), jnp.float32),
            f_state=f_init(),
            d_state=d_init_fn(draft_params, d_enc_one),
            cycles=jnp.zeros((), jnp.int32),
            accepted=jnp.zeros((), jnp.int32),
            hist=jnp.zeros((k_cap + 1,), jnp.int32),
            drafted=jnp.zeros((), jnp.int32),
        )

    def cycle_one(f_enc_one, d_enc_one, enc_mask, ext_ids,
                  c: _SpecCarry) -> _SpecCarry:
        _, verify, commit = _make_full_driver(
            full_params, fhps, spec_k, f_enc_one, enc_mask, ext_ids,
            cache_k=k_cap)
        _, d_step = get_family(dhps.model_family).beam_adapter(dhps)
        body = _spec_body(draft_params, fhps, spec_k, d_enc_one, enc_mask,
                          ext_ids, verify, commit, d_step)
        return body(c)

    return init_one, cycle_one


def _out_of_carry(c: _SpecCarry, T: int) -> SpecDecodeOutput:
    """Finalize one article's carry (batch-axis-agnostic: the slices
    below broadcast over a leading batch axis, so both the vmapped
    one-dispatch path and the adaptive host loop share it)."""
    length = c.t + 1  # generated tokens + START (the beam length rule)
    start = jnp.broadcast_to(jnp.asarray(START_ID, jnp.int32),
                             c.t.shape + (1,)) if c.t.ndim \
        else jnp.array([START_ID], jnp.int32)
    return SpecDecodeOutput(
        tokens=jnp.concatenate([start, c.tokens[..., :T]], axis=-1),
        length=length,
        avg_log_prob=c.sum_lp / length.astype(jnp.float32),
        attn_dists=c.attn[..., :T, :],
        p_gens=c.pgens[..., :T],
        cycles=c.cycles,
        drafted=c.drafted,
        accepted=c.accepted,
        accept_hist=c.hist,
    )


def _spec_one(full_params, draft_params, fhps: HParams, dhps: HParams,
              spec_k: int, f_enc_one, d_enc_one, enc_mask, ext_ids):
    """Speculative decode for ONE article (vmapped over the batch).
    fhps/dhps arrive with beam_size=1 — run_spec_decode, the one host
    entry, normalizes them so the jit cache key cannot fragment over a
    beam width the engine ignores."""
    init_one, cycle_one = _article_fns(full_params, draft_params, fhps,
                                       dhps, spec_k, spec_k)
    init = init_one(f_enc_one, d_enc_one, enc_mask, ext_ids)
    c = jax.lax.while_loop(
        lambda s: jnp.logical_not(s.done),
        lambda s: cycle_one(f_enc_one, d_enc_one, enc_mask, ext_ids, s),
        init)
    return _out_of_carry(c, fhps.max_dec_steps)


@functools.partial(jax.jit, static_argnames=("fhps", "dhps", "spec_k"))
def run_spec_decode_jit(full_params, draft_params, fhps: HParams,
                        dhps: HParams, arrays: Dict[str, Array],
                        spec_k: int) -> SpecDecodeOutput:
    """One compiled dispatch speculatively decodes the whole batch.
    Both models encode the article batch once; the per-article loop is
    vmapped.  Everything downstream of the encoders is shape-static —
    accept length, cycle count, and slot content are all traced.
    fhps/dhps must carry beam_size=1 (the engine is single-hypothesis;
    ``run_spec_decode`` normalizes so differing beam widths cannot
    fragment the jit cache)."""
    f_family = get_family(fhps.model_family)
    d_family = get_family(dhps.model_family)
    f_enc = f_family.beam_encode(full_params, fhps, arrays)
    d_enc = d_family.beam_encode(draft_params, dhps, arrays)
    fn = functools.partial(_spec_one, full_params, draft_params, fhps,
                           dhps, spec_k)
    return jax.vmap(fn)(f_enc, d_enc, arrays["enc_padding_mask"],
                        arrays["enc_batch_extend_vocab"])


def run_spec_decode(full_params, draft_params, hps: HParams,
                    arrays: Dict[str, np.ndarray],
                    controller: "SpecKController" = None,
                    real_mask=None) -> SpecDecodeOutput:
    """Host entry: resolve the draft shape (config.derive_draft_hps),
    dispatch once, return host numpy (run_beam_search's contract, plus
    the speculative stats).

    ``controller`` (or ``hps.spec_k_adaptive``) routes through the
    acceptance-adaptive engine instead: one dispatch per draft-verify
    cycle, k re-picked on the host between cycles — same output
    contract, same token exactness (pass a persistent controller to
    carry the learned acceptance estimate across batches, the
    decoder's pattern; ``real_mask`` keeps padding rows out of its
    observations)."""
    if controller is None and getattr(hps, "spec_k_adaptive", False):
        controller = SpecKController.from_hps(hps)
    if controller is not None:
        return run_spec_decode_adaptive(full_params, draft_params, hps,
                                        arrays, controller,
                                        real_mask=real_mask)
    fhps = hps.replace(beam_size=1)  # the verify path is single-hyp
    dhps = derive_draft_hps(hps).replace(beam_size=1, mode="decode")
    enc_arrays = {k: v for k, v in arrays.items() if k.startswith("enc_")}
    from textsummarization_on_flink_tpu import obs
    from textsummarization_on_flink_tpu.obs import profile as profile_lib

    # run_beam_search's compile telemetry, via the one shared compile
    # ledger (obs/profile.py, ISSUE 16) — one entry per distinct spec_k
    out = profile_lib.compiled_call(
        obs.registry_for(hps), "decode/spec_decode_jit",
        run_spec_decode_jit, full_params, draft_params, fhps, dhps,
        enc_arrays, int(hps.spec_k),
        key=int(hps.spec_k), phase="decode/spec_cycle")
    return SpecDecodeOutput(*[np.asarray(x) for x in out])


# --------------------------------------------------------------------------
# Acceptance-adaptive spec_k (ISSUE 12)
# --------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("fhps", "dhps", "k_cap"))
def spec_prepare_jit(full_params, draft_params, fhps: HParams,
                     dhps: HParams, arrays: Dict[str, Array], k_cap: int):
    """Encode the batch with both models and build the initial carry
    for the adaptive engine — ONE compile per (shapes, k_cap), shared
    by every k the controller later picks (the carry's k-dependent
    shapes ride k_cap, not the cycle's k)."""
    f_enc = get_family(fhps.model_family).beam_encode(full_params, fhps,
                                                      arrays)
    d_enc = get_family(dhps.model_family).beam_encode(draft_params, dhps,
                                                      arrays)
    init_one, _ = _article_fns(full_params, draft_params, fhps, dhps,
                               k_cap, k_cap)
    carry = jax.vmap(init_one)(f_enc, d_enc, arrays["enc_padding_mask"],
                               arrays["enc_batch_extend_vocab"])
    return f_enc, d_enc, carry


@functools.partial(jax.jit,
                   static_argnames=("fhps", "dhps", "spec_k", "k_cap"))
def spec_cycle_jit(full_params, draft_params, fhps: HParams, dhps: HParams,
                   f_enc, d_enc, enc_mask, ext_ids, carry, spec_k: int,
                   k_cap: int):
    """One draft-verify-commit cycle at ``spec_k`` for the whole batch
    (done articles pass through untouched).  One compile per DISTINCT
    spec_k — the warm set the controller walks is bounded by the
    committed [spec_k_min, spec_k_max] range (pinned by test)."""
    _, cycle_one = _article_fns(full_params, draft_params, fhps, dhps,
                                spec_k, k_cap)

    def one(f1, d1, m, x, c):
        return jax.lax.cond(
            c.done, lambda cc: cc,
            lambda cc: cycle_one(f1, d1, m, x, cc), c)

    return jax.vmap(one)(f_enc, d_enc, enc_mask, ext_ids, carry)


#: committed draft/full per-step cost ratios, read once per process
_RATIO_CACHE: Dict[str, float] = {}


def committed_draft_ratio(family: str, default: float = 0.5) -> float:
    """The committed draft/full per-step cost ratio the adaptive
    controller's progress-per-FLOP model prices draft steps at —
    BYTE_BUDGET.json spec.max_draft_flops_ratio (a CEILING, so the
    controller is conservative about how cheap drafting is).  Falls
    back to ``default`` when the budget file is absent (installed
    packages, stripped checkouts)."""
    if family not in _RATIO_CACHE:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "BYTE_BUDGET.json")
        try:
            with open(path, encoding="utf-8") as f:
                ratio = float(
                    json.load(f)["spec"]["max_draft_flops_ratio"][family])
        except (OSError, KeyError, TypeError, ValueError):
            ratio = float(default)
        _RATIO_CACHE[family] = ratio
    return _RATIO_CACHE[family]


class SpecKController:
    """Acceptance-adaptive draft length (ISSUE 12): start at k_start,
    track the measured accept histogram, and pick the k in
    [k_min, k_max] that maximizes expected progress per FLOP —
    ``expected_speedup(alpha, k, draft_ratio)``, the same
    bandwidth-model formula the BYTE_BUDGET.json spec gate pins.

    Pure host arithmetic on cumulative counts: the k trajectory is a
    DETERMINISTIC function of the observed accept sequence (pinned by
    test) — no wall clock, no RNG.  The per-position acceptance
    probability alpha is estimated from the histogram the verifier
    already emits: a cycle with accept length a < k is a successes and
    one failure (the rejection), a == k is k censored successes; a
    small symmetric prior keeps the first cycles from slamming k to a
    bound on one observation.
    """

    def __init__(self, k_min: int, k_start: int, k_max: int,
                 draft_ratio: float, prior_trials: float = 8.0,
                 prior_alpha: float = 0.5):
        if not 1 <= k_min <= k_start <= k_max:
            raise ValueError(
                f"need 1 <= k_min <= k_start <= k_max, got "
                f"[{k_min}, {k_start}, {k_max}]")
        if draft_ratio <= 0:
            raise ValueError(f"draft_ratio must be > 0, got {draft_ratio}")
        self.k = int(k_start)
        self.k_min = int(k_min)
        self.k_max = int(k_max)
        self.draft_ratio = float(draft_ratio)
        self._succ = float(prior_alpha) * float(prior_trials)
        self._trials = float(prior_trials)
        self.cycles = 0
        self.drafted = 0
        self.accepted = 0

    @classmethod
    def from_hps(cls, hps: HParams,
                 draft_ratio: float = None) -> "SpecKController":
        """The ONE construction path for configured jobs: bounds from
        config.resolve_spec_bounds, cost ratio from the committed
        budget (unless injected — tests pin trajectories with explicit
        ratios)."""
        from textsummarization_on_flink_tpu.config import resolve_spec_bounds

        k_min, k_start, k_max = resolve_spec_bounds(hps)
        if draft_ratio is None:
            draft_ratio = committed_draft_ratio(hps.model_family)
        return cls(k_min, k_start, k_max, draft_ratio)

    @property
    def alpha(self) -> float:
        """Current per-position acceptance-probability estimate."""
        return self._succ / self._trials

    @property
    def mean_k(self) -> float:
        """Realized mean spec_k over observed cycles (k_start before
        any observation)."""
        return self.drafted / self.cycles if self.cycles else float(self.k)

    def observe(self, hist_counts, k_used: int) -> int:
        """Fold one cycle batch's accept-histogram DELTA (counts per
        accept length 0..k_used, padded rows past k_used ignored) into
        the estimate and re-pick k.  Returns the new k."""
        k_used = int(k_used)
        counts = [int(x) for x in hist_counts]
        for a, n in enumerate(counts[:k_used + 1]):
            if n <= 0:
                continue
            self.cycles += n
            self.drafted += n * k_used
            self.accepted += n * a
            self._succ += n * a
            self._trials += n * (a + 1 if a < k_used else a)
        return self.update()

    def update(self) -> int:
        """Re-pick k = argmax expected progress per FLOP at the current
        alpha (ties break LOW — never pay extra draft steps for equal
        expected progress)."""
        alpha = self.alpha
        best_k, best = self.k_min, -1.0
        for k in range(self.k_min, self.k_max + 1):
            s = expected_speedup(alpha, k, self.draft_ratio)
            if s > best + 1e-12:
                best, best_k = s, k
        self.k = best_k
        return self.k


def run_spec_decode_adaptive(full_params, draft_params, hps: HParams,
                             arrays: Dict[str, np.ndarray],
                             controller: SpecKController,
                             real_mask=None) -> SpecDecodeOutput:
    """The acceptance-adaptive host loop (ISSUE 12): prepare once, then
    one ``spec_cycle_jit`` dispatch per draft-verify cycle, with the
    controller re-picking k from the accept-histogram delta between
    cycles.  The per-cycle host sync IS the adaptivity price (stated in
    PERF.md); everything inside a cycle stays one fused dispatch, and
    the compile warm set is one entry per distinct k.

    ``real_mask`` [B] (bool) restricts the controller's observations to
    real batch rows — padding repeats (batching.py real_mask semantics)
    decode too, but must not multiply-count one article's acceptance
    into the estimate the k policy runs on (the same real-rows rule the
    decoder applies to the decode/spec_* counters)."""
    fhps = hps.replace(beam_size=1)  # the verify path is single-hyp
    dhps = derive_draft_hps(hps).replace(beam_size=1, mode="decode")
    k_cap = controller.k_max
    enc_arrays = {k: v for k, v in arrays.items() if k.startswith("enc_")}
    from textsummarization_on_flink_tpu import obs
    from textsummarization_on_flink_tpu.obs import profile as profile_lib

    reg = obs.registry_for(hps)
    prof = profile_lib.profiler_for(reg)
    # the committed warm set for the cycle kernel: one compile per
    # distinct k the controller can pick (BYTE_BUDGET.json "adaptive";
    # growth beyond it is a compile storm)
    prof.set_compile_budget("decode/spec_cycle_jit",
                            int(controller.k_max) - int(controller.k_min)
                            + 1)
    f_enc, d_enc, carry = profile_lib.compiled_call(
        reg, "decode/spec_prepare_jit", spec_prepare_jit,
        full_params, draft_params, fhps, dhps, enc_arrays, k_cap,
        key=int(k_cap))
    enc_mask = jnp.asarray(enc_arrays["enc_padding_mask"])
    ext_ids = jnp.asarray(enc_arrays["enc_batch_extend_vocab"])
    real = (np.asarray(real_mask, dtype=bool) if real_mask is not None
            else np.ones(enc_arrays["enc_batch"].shape[0], dtype=bool))
    prev_hist = 0  # broadcasts against the first fetched histogram
    # every cycle commits >= 1 token per live article, so max_dec_steps
    # cycles is a hard completion bound (not a tunable)
    k_cap = int(k_cap)
    for _ in range(fhps.max_dec_steps):
        k = controller.k  # host int by construction (SpecKController)
        # the ledger key is k itself (a host int by construction —
        # SpecKController.k never holds a device value)
        carry = profile_lib.compiled_call(
            reg, "decode/spec_cycle_jit", spec_cycle_jit,
            full_params, draft_params, fhps, dhps, f_enc, d_enc,
            enc_mask, ext_ids, carry, k, k_cap,
            key=k, phase="decode/spec_cycle")
        # the sanctioned between-cycle sync: ONE D2H fetch hands the
        # controller this cycle's accept histogram and the done flags
        # together (module docstring)
        hist, done = jax.device_get((carry.hist, carry.done))  # tslint: disable=TS002 — the adaptive contract's one per-cycle D2H read
        controller.observe((hist - prev_hist)[real].sum(axis=0), k)
        prev_hist = hist
        if done.all():
            break
    out = _out_of_carry(carry, fhps.max_dec_steps)
    return SpecDecodeOutput(*[np.asarray(x) for x in out])


def expected_speedup(alpha: float, spec_k: int, draft_ratio: float) -> float:
    """Expected spec-tier speedup over plain greedy under the
    bandwidth-bound decode model (PERF.md "Speculative tier"): with
    per-position acceptance probability ``alpha``, a cycle emits
    E = (1 - alpha^(k+1)) / (1 - alpha) tokens in expectation and costs
    (k+1) draft steps (the +1 is the resync step) plus ONE full-model
    invocation — the parallel verify streams the full model's weights
    once for all k+1 positions, which is what makes a verify invocation
    ~one full step on a bandwidth-bound decoder.  ``draft_ratio`` is
    the committed draft/full per-step cost ratio (BYTE_BUDGET.json
    "spec").  Greedy costs 1 full step per token, so speedup =
    E / ((k+1) * ratio + 1)."""
    a = min(max(float(alpha), 0.0), 1.0)
    if a >= 1.0:
        e = float(spec_k + 1)
    else:
        e = (1.0 - a ** (spec_k + 1)) / (1.0 - a)
    return e / ((spec_k + 1) * float(draft_ratio) + 1.0)
