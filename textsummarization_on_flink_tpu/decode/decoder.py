"""Beam-search decode driver: checkpoint loading, decode loop, writers.

Rebuilds the reference BeamSearchDecoder
(/root/reference/src/main/python/pointer-generator/decode.py) TPU-first:
instead of one encoder `sess.run` plus ~100 single-step `sess.run`s per
article (decode.py:95-106 -> beam_search.py:118), each batch of articles is
decoded in ONE device dispatch (decode/beam_search.py), and the TF
Saver/session machinery is replaced by the npz checkpoint layer.

Preserved behavior:
  * decode-dir naming from the checkpoint name + key hps
    (`get_decode_dir_name`, decode.py:303-313);
  * single-pass mode writes pyrouge-layout reference/decoded files and runs
    ROUGE at the end (decode.py:133-147, 187-222, 268-301);
  * continuous mode periodically reloads the newest checkpoint
    (SECS_UNTIL_NEW_CKPT=60, decode.py:36,149-157) and writes the
    attention-visualizer JSON (decode.py:225-249);
  * `[STOP]`-truncation of the emitted token stream (decode.py:112-118);
  * html-escaping of <, > in outputs (`make_html_safe`, decode.py:252-255);
  * streaming results carry (uuid, article, summary, reference) rows with
    the summary sentence-split on '.' (`write_for_flink`, decode.py:159-185).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.obs import profile as profile_lib
from textsummarization_on_flink_tpu.checkpoint import checkpointer as ckpt_lib
from textsummarization_on_flink_tpu.config import (
    SERVE_TIERS,
    HParams,
    bucket_for,
    derive_draft_hps,
    parse_bucket_spec,
    resolve_arena_pages,
    resolve_enc_block,
    resolve_spec_bounds,
)
from textsummarization_on_flink_tpu.data import oov as oov_lib
from textsummarization_on_flink_tpu.data.batching import Batch
from textsummarization_on_flink_tpu.data.vocab import STOP_DECODING, Vocab
from textsummarization_on_flink_tpu.decode import beam_search
from textsummarization_on_flink_tpu.decode.arena import (
    ArenaExhaustedError,
    PageArena,
)
from textsummarization_on_flink_tpu.evaluate import rouge
from textsummarization_on_flink_tpu.resilience.policy import Deadline

log = logging.getLogger(__name__)

SECS_UNTIL_NEW_CKPT = 60  # decode.py:36


def make_html_safe(s: str) -> str:
    """decode.py:252-255."""
    return s.replace("<", "&lt;").replace(">", "&gt;")


def words_to_sentences(decoded_words: List[str]) -> List[str]:
    """Split a decoded word stream into '.'-terminated sentences
    (decode.py:193-201 / write_for_flink :166-175)."""
    words = list(decoded_words)
    sents: List[str] = []
    while words:
        try:
            fst_period_idx = words.index(".")
        except ValueError:
            fst_period_idx = len(words) - 1
        sent = words[: fst_period_idx + 1]
        words = words[fst_period_idx + 1:]
        sents.append(" ".join(sent))
    return sents


def get_decode_dir_name(hps: HParams, ckpt_path: Optional[str]) -> str:
    """decode.py:303-313 naming (ckpt basename + key decode hps)."""
    if ckpt_path is not None:
        ckpt_name = "ckpt-" + os.path.basename(ckpt_path).split("-")[-1].split(".")[0]
    else:
        ckpt_name = "ckpt-none"
    return (f"decode_{ckpt_name}_{hps.max_enc_steps}maxenc_"
            f"{hps.beam_size}beam_{hps.min_dec_steps}mindec_"
            f"{hps.max_dec_steps}maxdec")


class DecodedResult:
    """One article's decode output (the streaming-row payload)."""

    def __init__(self, uuid: str, article: str, decoded_words: List[str],
                 reference: str, abstract_sents: List[str],
                 attn_dists: Optional[np.ndarray] = None,
                 p_gens: Optional[np.ndarray] = None,
                 degraded: bool = False, tier: str = "beam",
                 params_fingerprint: str = ""):
        self.uuid = uuid
        self.article = article
        self.decoded_words = decoded_words
        self.reference = reference
        self.abstract_sents = abstract_sents
        self.attn_dists = attn_dists
        self.p_gens = p_gens
        # True when the decode deadline forced beam search down to greedy
        # (RESILIENCE.md graceful degradation; hps.decode_deadline_secs)
        self.degraded = degraded
        # the quality tier that produced this result (SERVING.md
        # "Quality tiers": beam|greedy|spec|draft)
        self.tier = tier
        # fingerprint of the params snapshot that DECODED this result
        # (ISSUE 14): the summary cache files entries under it, so a
        # result produced just before a hot-swap lands under the
        # snapshot that made it, never the one that replaced it ("" =
        # producer without the surface: stubs, sim engines)
        self.params_fingerprint = params_fingerprint

    @property
    def decoded_sents(self) -> List[str]:
        return [make_html_safe(s) for s in words_to_sentences(self.decoded_words)]

    @property
    def summary(self) -> str:
        return " ".join(self.decoded_sents)

    def as_row(self) -> Tuple[str, str, str, str]:
        """(uuid, article, summary, reference) — the write_for_flink row
        (flink_writer.py:22-34 field set)."""
        return (self.uuid, self.article, self.summary, self.reference)


class BeamSearchDecoder:
    """Decode loop driver (decode.py:42-157).

    params_source: either a static params pytree (`params=`) or a train
    dir to load checkpoints from (`train_dir=`, with load_ckpt retry —
    util.py:29-41 — and 60s reloads in continuous mode).
    """

    def __init__(self, hps: HParams, vocab: Vocab, batcher: Any,
                 params: Optional[Any] = None,
                 train_dir: Optional[str] = None,
                 decode_root: Optional[str] = None,
                 max_ckpt_retries: Optional[int] = None,
                 draft_params: Optional[Any] = None):
        if params is None and train_dir is None:
            raise ValueError("need params or train_dir")
        self._hps = hps
        self._vocab = vocab
        self._batcher = batcher
        self._train_dir = train_dir
        self._max_ckpt_retries = max_ckpt_retries
        # guards the (params, ckpt_path) PAIR: continuous-mode reloads
        # (and the serve/ hot-swap) replace both together, and a
        # concurrent decode_batch must never observe a half-swapped
        # state (new params with the old checkpoint name, or vice versa)
        self._params_lock = threading.Lock()
        self._ckpt_path: Optional[str] = None
        # (params object, its content fingerprint) — the
        # params_fingerprint property's one-sha-per-swap memo
        self._fp_cache: Optional[Tuple[Any, str]] = None
        # observability (`decode/` namespace, OBSERVABILITY.md):
        # per-request latency percentiles, finished beams, token volume
        # (tokens/sec = decode/tokens_total over decode/busy_seconds_total),
        # and continuous-mode checkpoint reloads
        self._obs = obs.registry_for(hps)
        self._m_latency = self._obs.histogram("decode/request_latency_seconds")
        self._c_requests = self._obs.counter("decode/requests_total")
        self._c_beams = self._obs.counter("decode/beams_finished_total")
        self._c_tokens = self._obs.counter("decode/tokens_total")
        self._c_busy = self._obs.counter("decode/busy_seconds_total")
        self._c_reloads = self._obs.counter("decode/ckpt_reloads_total")
        # resilience (RESILIENCE.md): per-request Deadline + graceful
        # degradation.  `_beam_secs` is an EMA of observed FULL-BEAM
        # dispatch latency; once it exists and a request's remaining
        # budget cannot cover it, the dispatch runs greedy (beam_size=1)
        # and its results are tagged degraded=True.
        self._c_degraded = self._obs.counter(
            "resilience/decode_degraded_total")
        self._g_beam_est = self._obs.gauge(
            "resilience/decode_beam_latency_est_seconds")
        self._beam_secs: Optional[float] = None
        # the FIRST full-beam dispatch carries the jit compile (seconds
        # to minutes); recording it would lock every later request into
        # greedy, so the EMA only starts at the second full-beam dispatch
        self._beam_warm = False
        # ---- speculative tier (SERVING.md "Quality tiers"; ISSUE 10) ----
        # draft params ride the SAME lock as the full pair: with
        # spec_draft="map" a checkpoint hot-swap re-derives the draft,
        # and a spec dispatch must never pair old draft with new full
        self._draft_params = draft_params
        # accept-length histogram buckets span the FULL committed k
        # range (0..spec_k_max via resolve_spec_bounds): under the
        # adaptive controller, cycles run at k up to spec_k_max, and
        # spec_k-sized buckets would pile every longer acceptance into
        # the overflow bin (the ISSUE-12 satellite fix — same shape of
        # fix as PR 11's serve/prefill_bucket_len)
        _, _, spec_k_max = resolve_spec_bounds(hps)
        self._h_accept = self._obs.histogram(
            "decode/spec_accept_len",
            buckets=[float(i) for i in range(0, spec_k_max + 1)])
        self._c_spec_cycles = self._obs.counter("decode/spec_cycles_total")
        self._c_spec_drafted = self._obs.counter(
            "decode/spec_draft_tokens_total")
        self._c_spec_accepted = self._obs.counter(
            "decode/spec_accepted_tokens_total")
        # acceptance-adaptive spec_k (ISSUE 12): ONE controller per
        # decoder — it adapts k between cycles inside a dispatch and
        # carries the learned acceptance estimate across requests; its
        # current pick is exported as a gauge.  Mutated only on the
        # dispatch path (the serve layer runs one dispatch thread).
        self._spec_ctl = None
        self._g_spec_k = self._obs.gauge("decode/spec_k_current")
        # documented semantics (OBSERVABILITY.md): the gauge reads
        # spec_k when non-adaptive, the controller's live pick otherwise
        self._g_spec_k.set(float(hps.spec_k))
        if getattr(hps, "spec_k_adaptive", False):
            from textsummarization_on_flink_tpu.decode import speculative

            self._spec_ctl = speculative.SpecKController.from_hps(hps)
            self._g_spec_k.set(float(self._spec_ctl.k))
        self._params = params
        if params is None:
            self._load_params()
        if self._draft_params is None and hps.spec_draft:
            from textsummarization_on_flink_tpu.models import avg_attention

            self._draft_params = avg_attention.make_draft_params(
                hps, self._params, seed=hps.seed)

        self._sharded_search = None
        self._mesh_plan = None
        if hps.dp * hps.tp * hps.sp > 1:
            # multi-chip serving: articles shard over dp, beams chip-local
            from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib

            mesh_lib.validate_divisibility(hps, self._params)
            self._mesh_plan = mesh_lib.make_mesh(hps)
            self._sharded_search = mesh_lib.make_sharded_beam_search(
                self._mesh_plan, params=self._params)

        root = decode_root or os.path.join(hps.log_root or ".",
                                           hps.exp_name or "exp")
        if hps.single_pass:
            self._decode_dir = os.path.join(
                root, get_decode_dir_name(hps, self._ckpt_path))
            if os.path.exists(self._decode_dir):
                raise FileExistsError(
                    f"single_pass decode directory {self._decode_dir} should "
                    "not already exist")  # decode.py:70-71
        else:
            self._decode_dir = os.path.join(root, "decode")
        os.makedirs(self._decode_dir, exist_ok=True)
        self._rouge_ref_dir = os.path.join(self._decode_dir, "reference")
        self._rouge_dec_dir = os.path.join(self._decode_dir, "decoded")
        if hps.single_pass:
            os.makedirs(self._rouge_ref_dir, exist_ok=True)
            os.makedirs(self._rouge_dec_dir, exist_ok=True)

    # -- checkpoint handling --
    def _params_snapshot(self) -> Tuple[Any, Optional[str]]:
        """Atomic read of the (params, ckpt_path) pair — the one sanctioned
        way for a dispatch to pick up weights while reloads may run."""
        with self._params_lock:
            return self._params, self._ckpt_path

    @property
    def params_fingerprint(self) -> str:
        """Content fingerprint of the ACTIVE ``_params_snapshot``
        (``checkpoint.checkpointer.content_fingerprint`` — the one
        scheme the distill teacher sidecar also uses) — the serve
        layer's cache key and /healthz surface (SERVING.md "Front
        door").  Cached per swapped-in params OBJECT: the sha runs once
        per checkpoint hot-swap, not per request (the cache tuple holds
        the source tree, so object identity can never false-hit on a
        recycled address)."""
        params, _ = self._params_snapshot()
        cached = self._fp_cache
        if cached is not None and cached[0] is params:
            return cached[1]
        fp = ckpt_lib.content_fingerprint(params)
        self._fp_cache = (params, fp)
        return fp

    def _load_params(self) -> None:
        # load + decode OUTSIDE the lock (seconds of IO must not stall
        # concurrent dispatches); only the pointer swap is locked
        path, flat = ckpt_lib.load_ckpt(self._train_dir,
                                        max_retries=self._max_ckpt_retries)
        state = ckpt_lib.arrays_to_state(flat)
        draft = None
        if self._hps.spec_draft == "map":
            # the mapped draft is a VIEW of the full checkpoint: derive
            # it from the same params the swap installs (outside the
            # lock, like the load), so spec dispatches never pair a
            # fresh full model with a stale draft
            from textsummarization_on_flink_tpu.models import avg_attention

            draft = avg_attention.make_draft_params(
                self._hps, state.params, seed=self._hps.seed)
        with self._params_lock:
            self._params = state.params
            self._ckpt_path = path
            if draft is not None:
                self._draft_params = draft
        log.info("decoder loaded checkpoint %s", path)

    def maybe_reload_checkpoint(self, last_load: float) -> float:
        """Continuous-serving checkpoint refresh (decode.py:149-157).

        ``last_load`` is a ``time.monotonic()`` reference: the 60s reload
        cadence is a duration, and a wall-clock jump (NTP slew, suspend)
        must neither storm reloads nor starve them (TS003).

        Thread-safe hot-swap (ISSUE 4 satellite): the (params,
        ckpt_path) pair swaps under ``_params_lock``, so a concurrent
        ``decode_batch`` (the serve/ dispatch thread, or any
        out-of-band caller) sees either the old pair or the new one —
        never a half-swap.  Each swap bumps
        ``decode/ckpt_reloads_total``.  The sharded (mesh) search closes
        over its initial params and does NOT hot-swap."""
        if self._train_dir is None:
            return last_load
        if time.monotonic() - last_load < SECS_UNTIL_NEW_CKPT:
            return last_load
        latest = ckpt_lib.latest_checkpoint(self._train_dir)
        _, current = self._params_snapshot()
        if latest is not None and latest != current:
            log.info("Decoder has been decoding for %.0f seconds; loading "
                     "new checkpoint", time.monotonic() - last_load)
            self._load_params()
            self._c_reloads.inc()
        return time.monotonic()

    # -- decoding --
    def should_degrade(self, deadline: Deadline) -> bool:
        """True when the remaining request budget cannot cover a
        full-beam dispatch (RESILIENCE.md degradation contract) — the
        serve layer's per-REQUEST re-tiering predicate (SERVING.md
        "Quality tiers").

        Requires a latency estimate from a completed full-beam dispatch
        AFTER the compile-inclusive first one — early requests are never
        degraded.  Single-host path
        only: the sharded search is jit-built once for the mesh plan and
        cannot swap beam width per request."""
        return (deadline.bounded
                and self._sharded_search is None
                and self._hps.beam_size > 1
                and self._beam_secs is not None
                and deadline.remaining() < self._beam_secs)

    _should_degrade = should_degrade  # historical internal name

    @property
    def has_draft(self) -> bool:
        """Whether the spec/draft tiers are servable (a draft model is
        configured — mapped, fresh, or injected)."""
        return self._draft_params is not None

    @property
    def sharded(self) -> bool:
        """True on a dp/tp mesh: the sharded search is jit-built once
        for the mesh plan, so only the beam tier is servable (the serve
        layer rejects other tiers at submit)."""
        return self._sharded_search is not None

    def _spec_snapshot(self) -> Tuple[Any, Any]:
        """Atomic (full params, draft params) read — the spec tier's
        analogue of ``_params_snapshot`` (a hot-swap replaces both under
        the same lock, so a dispatch never pairs mismatched models)."""
        with self._params_lock:
            return self._params, self._draft_params

    def decode_batch(self, batch: Batch,
                     deadline: Optional[Deadline] = None,
                     tier: Optional[str] = None) -> List[DecodedResult]:
        """One device dispatch for the whole batch; returns one result per
        REAL input row (``batch.real_mask``).  Padding rows — beam
        repetition in decode 'repeat' mode (batcher.py:344-347) and
        trickle/tail padding — are tagged by the batcher and dropped here;
        two legitimately identical input rows each get a result, matching
        the reference's one-result-per-record contract (decode.py:159-185).

        Resilience: every call carries a Deadline — the caller's, or one
        built from ``hps.decode_deadline_secs`` (0 = unbounded, never
        degrade).  When the budget is short of the full-beam latency
        estimate the dispatch degrades to greedy (beam_size=1); results
        are tagged ``degraded=True`` and counted in
        ``resilience/decode_degraded_total``.

        Quality tiers (SERVING.md "Quality tiers"; ISSUE 10): an
        explicit ``tier`` (beam|greedy|spec|draft) dispatches exactly
        that tier — the serve layer already made the per-request
        degradation decision, so the internal deadline ladder is
        skipped.  ``tier=None`` keeps the historical behavior (beam,
        degrading to greedy under deadline pressure)."""
        if deadline is None:
            deadline = Deadline.after(
                getattr(self._hps, "decode_deadline_secs", 0.0))
        explicit = tier is not None
        if explicit:
            if tier not in SERVE_TIERS:
                raise ValueError(
                    f"tier must be one of {SERVE_TIERS}, got {tier!r}")
            degraded = False
            eff_tier = tier
        else:
            degraded = self.should_degrade(deadline)
            eff_tier = "greedy" if degraded else "beam"
        t0 = time.perf_counter()
        with obs.spans.span(self._obs, "decode/batch", tier=eff_tier):
            results = self._decode_batch_inner(batch, tier=eff_tier)
        dt = time.perf_counter() - t0
        if degraded:
            for res in results:
                res.degraded = True
            self._c_degraded.inc(len(results))
            log.warning("decode deadline short of full-beam estimate "
                        "(%.3fs remaining < %.3fs est); degraded %d "
                        "result(s) to greedy", deadline.remaining(),
                        self._beam_secs, len(results))
        elif eff_tier == "beam":
            if not self._beam_warm:
                self._beam_warm = True  # compile-inclusive sample: discard
            else:
                # EMA of full-beam dispatch latency (greedy/spec/draft
                # dispatches and compile times must not poison the
                # estimate the degradation ladder keys on)
                self._beam_secs = (dt if self._beam_secs is None
                                   else 0.7 * self._beam_secs + 0.3 * dt)
                self._g_beam_est.set(self._beam_secs)
        self._c_busy.inc(dt)
        # requests in a batch share one dispatch: the batch wall time IS
        # each request's observed latency
        for res in results:
            self._m_latency.observe(dt)
            self._c_tokens.inc(len(res.decoded_words))
        self._c_requests.inc(len(results))
        self._c_beams.inc(len(results))
        return results

    def _decode_batch_inner(self, batch: Batch,
                            tier: str = "beam") -> List[DecodedResult]:
        # one atomic params read per dispatch: a checkpoint hot-swap
        # landing mid-batch affects the NEXT dispatch, never this one
        params, _ = self._params_snapshot()
        if self._sharded_search is not None:
            if tier != "beam":
                raise ValueError(
                    f"sharded (mesh) serving supports the beam tier only "
                    f"(the search is jit-built once for the mesh plan); "
                    f"got tier={tier!r}")
            from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib

            enc_only = {k: v for k, v in batch.as_arrays().items()
                        if k.startswith("enc_")}
            raw = self._sharded_search(
                params, mesh_lib.shard_batch(self._mesh_plan, enc_only))
            out = beam_search.BeamSearchOutput(
                *[np.asarray(x) for x in raw])
        elif tier == "spec":
            from textsummarization_on_flink_tpu.decode import speculative

            full, draft = self._spec_snapshot()
            if draft is None:
                raise ValueError(
                    "spec tier needs a draft model: set hps.spec_draft "
                    "('map'/'fresh') or pass draft_params=")
            real = np.asarray(batch.real_mask, dtype=bool)
            out = speculative.run_spec_decode(full, draft, self._hps,
                                              batch.as_arrays(),
                                              controller=self._spec_ctl,
                                              real_mask=real)
            if self._spec_ctl is not None:
                self._g_spec_k.set(float(self._spec_ctl.k))
            self._c_spec_cycles.inc(int(out.cycles[real].sum()))
            self._c_spec_drafted.inc(int(out.drafted[real].sum()))
            self._c_spec_accepted.inc(int(out.accepted[real].sum()))
            # accept_hist already holds per-length cycle counts: fold
            # the batch once and record O(spec_k) weighted observes,
            # not one lock acquisition per verify cycle
            per_len = out.accept_hist[real].sum(axis=0)
            for a, count in enumerate(per_len):
                self._h_accept.observe(float(a), n=int(count))
        elif tier == "draft":
            _, draft = self._spec_snapshot()
            if draft is None:
                raise ValueError(
                    "draft tier needs a draft model: set hps.spec_draft "
                    "('map'/'fresh') or pass draft_params=")
            dhps = derive_draft_hps(self._hps).replace(beam_size=1,
                                                       mode="decode")
            out = beam_search.run_beam_search(draft, dhps,
                                              batch.as_arrays())
        else:
            hps = (self._hps.replace(beam_size=1) if tier == "greedy"
                   else self._hps)
            out = beam_search.run_beam_search(params, hps,
                                              batch.as_arrays())
        results: List[DecodedResult] = []
        for b in range(len(batch.original_articles)):
            if not batch.real_mask[b]:
                continue
            results.append(self._make_result(
                out.tokens[b], int(out.length[b]), out.attn_dists[b],
                out.p_gens[b], uuid=batch.uuids[b],
                article=batch.original_articles[b],
                reference=batch.references[b],
                abstract_sents=batch.original_abstracts_sents[b],
                art_oovs=batch.art_oovs[b], tier=tier))
        return results

    def _make_result(self, tokens, length: int, attn_dists, p_gens, *,
                     uuid: str, article: str, reference: str,
                     abstract_sents: List[str],
                     art_oovs: List[str], tier: str = "beam",
                     ) -> DecodedResult:
        """One article's raw beam output -> DecodedResult: START strip,
        id->word mapping through the article's OOVs, [STOP] truncation
        (decode.py:112-118).  Shared by the batch path and the slot
        engine so the two serving modes emit identical rows."""
        output_ids = [int(t) for t in tokens[1:length]]  # strip START
        decoded_words = oov_lib.outputids2words(
            output_ids, self._vocab, art_oovs)
        try:
            fst_stop_idx = decoded_words.index(STOP_DECODING)
            decoded_words = decoded_words[:fst_stop_idx]
        except ValueError:
            pass
        return DecodedResult(
            uuid=uuid,
            article=article,
            decoded_words=decoded_words,
            reference=reference,
            abstract_sents=abstract_sents,
            attn_dists=attn_dists[: max(len(decoded_words), 1)],
            p_gens=p_gens[: max(len(decoded_words), 1)],
            tier=tier,
            # the fingerprint memo is keyed on the snapshot object, so
            # this is a dict read per result, not a sha — and a swap
            # landing mid-batch at worst stamps the NEW snapshot on a
            # result the old one decoded, which only costs a cache miss
            params_fingerprint=self.params_fingerprint)

    def slot_engine(self, slots: int, chunk: int) -> "SlotDecodeEngine":
        """The continuous-batching engine over this decoder's params
        (SERVING.md 'Continuous batching'): `slots` resident articles
        decoded in `chunk`-step pieces with in-flight refill."""
        return SlotDecodeEngine(self, slots, chunk)

    def decode(self, with_rouge: bool = True,
               result_sink: Optional[Callable[[DecodedResult], None]] = None,
               max_batches: int = 0, log_results: bool = True,
               ) -> Optional[Dict[str, Dict[str, float]]]:
        """The main loop (decode.py:131-157).

        single_pass: decode everything once, write rouge files, then
        evaluate (when with_rouge).  Otherwise: decode forever (or until the
        batcher ends / max_batches), pushing results to `result_sink`
        immediately — no buffering, the Issue-6 fix — reloading fresh
        checkpoints every 60s.

        log_results=False suppresses the continuous-mode article/summary
        INFO logging and the per-result attn_vis_data.json rewrite — the
        serving path (pipeline transform) wants results through the sink
        only, not an unbounded per-record disk write.
        """
        t_last = time.monotonic()
        counter = 0
        n_batches = 0
        while True:
            batch = self._batcher.next_batch()
            if batch is None:
                if self._hps.single_pass:
                    log.info("Decoder has finished reading dataset for "
                             "single_pass.")
                    break
                log.info("batcher exhausted; stopping decode loop")
                break
            t0 = time.monotonic()
            results = self.decode_batch(batch)
            log.info("decoded batch of %d article(s) in %.3f s",
                     len(results), time.monotonic() - t0)
            for res in results:
                if self._hps.single_pass:
                    self.write_for_rouge(res, counter)
                    counter += 1
                elif log_results:
                    log.info("ARTICLE: %s", res.article)
                    log.info("GENERATED SUMMARY: %s", res.summary)
                    self.write_for_attnvis(res)
                if result_sink is not None:
                    result_sink(res)  # immediate flush
            n_batches += 1
            if max_batches and n_batches >= max_batches:
                break
            if not self._hps.single_pass:
                t_last = self.maybe_reload_checkpoint(t_last)
        if self._hps.single_pass and with_rouge and counter > 0:
            log.info("Output has been saved in %s and %s. Now starting "
                     "ROUGE eval...", self._rouge_ref_dir, self._rouge_dec_dir)
            results_dict = rouge.rouge_eval(self._rouge_ref_dir,
                                            self._rouge_dec_dir)
            rouge.rouge_log(results_dict, self._decode_dir)
            return results_dict
        return None

    # -- writers --
    def write_for_rouge(self, res: DecodedResult, ex_index: int) -> None:
        """pyrouge file layout (decode.py:187-222)."""
        decoded_sents = res.decoded_sents
        reference_sents = [make_html_safe(s) for s in res.abstract_sents]
        ref_file = os.path.join(self._rouge_ref_dir,
                                f"{ex_index:06d}_reference.txt")
        decoded_file = os.path.join(self._rouge_dec_dir,
                                    f"{ex_index:06d}_decoded.txt")
        with open(ref_file, "w", encoding="utf-8") as f:
            for idx, sent in enumerate(reference_sents):
                f.write(sent + ("\n" if idx < len(reference_sents) - 1 else ""))
        with open(decoded_file, "w", encoding="utf-8") as f:
            for idx, sent in enumerate(decoded_sents):
                f.write(sent + ("\n" if idx < len(decoded_sents) - 1 else ""))
        log.info("Wrote example %i to file", ex_index)

    def write_for_attnvis(self, res: DecodedResult) -> None:
        """attn_vis JSON (decode.py:225-249 field layout)."""
        article_lst = res.article.split()
        to_write = {
            "article_lst": [make_html_safe(t) for t in article_lst],
            "decoded_lst": [make_html_safe(t) for t in res.decoded_words],
            "abstract_str": make_html_safe(" ".join(res.abstract_sents)),
            "attn_dists": (res.attn_dists[:, : len(article_lst)].tolist()
                           if res.attn_dists is not None else []),
        }
        if self._hps.pointer_gen and res.p_gens is not None:
            to_write["p_gens"] = res.p_gens.tolist()
        output_fname = os.path.join(self._decode_dir, "attn_vis_data.json")
        with open(output_fname, "w", encoding="utf-8") as f:
            json.dump(to_write, f)
        log.info("Wrote visualization data to %s", output_fname)


class PrefilledArticle(NamedTuple):
    """Host-side handle for one article through the PREFILL stage
    (ISSUE 11): the device-resident PrefillState (encoder +
    cross-attention cache at the article's bucket, padded to the
    resident width) plus the request bookkeeping pack needs."""

    example: Any  # the SummaryExample (uuid/reference/OOVs travel here)
    state: Any  # beam_search.PrefillState
    bucket: int  # the encoder bucket the prefill compiled/ran at


class SlotDecodeEngine:
    """Host driver of beam_search's persistent slot kernels (ISSUE 6),
    disaggregated into a bucketed prefill stage and a length-masked
    decode stage (ISSUE 11).

    Owns the [slots, beam, ...] resident state and the per-slot activity
    mask; the scheduler above it (serve/batcher.ContinuousBatcher) owns
    request bookkeeping.  Single-threaded by design — the one
    continuous-dispatch thread calls prefill/pack/step/unpack; the ONLY
    chunk boundary host sync is reading the `finished` mask in step().

    Shape discipline: the RESIDENT state keeps one shape
    (``hps.max_enc_steps`` wide — that is what makes slot recycling
    shape-stable), so the decode kernels warm exactly four compiles
    (init/pack/step/unpack) with slot index, occupancy, and valid
    lengths all traced.  The COST no longer follows the shape: prefill
    runs the encoder at the article's micro-batcher bucket
    (``serve_buckets`` — one prefill compile per bucket), and each
    decode chunk's cross-attention is bounded by the longest active
    resident's true length (beam_search.step_slots_jit).  Compile
    activity stays visible in the existing
    ``decode/compile_cache_*_total`` counters.

    Checkpoint hot-swap: each kernel call reads the decoder's
    ``_params_snapshot()``, so a between-batch reload lands at the NEXT
    chunk boundary — resident articles finish under the new params
    (documented in SERVING.md; same shapes, so no recompile).

    Multi-chip serving (ISSUE 8): on a dp x tp mesh the resident
    [slots, ...] state shards over dp and params tp-shard, both against
    the sharding registry (parallel/sharding.py) — the same layout
    story as training and the micro-batch sharded search.  Slots must
    divide by dp.  The kernels themselves are unchanged: sharded inputs
    compile to a sharded program, and the engine re-pins the state to
    the registry specs after each step so GSPMD's output layout can
    never drift from the registry's.
    """

    def __init__(self, decoder: BeamSearchDecoder, slots: int, chunk: int):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if chunk < 1:
            raise ValueError(f"refill chunk must be >= 1, got {chunk}")
        self._dec = decoder
        self._hps = decoder._hps
        self.slots = slots
        self.chunk = min(chunk, self._hps.max_dec_steps)
        self._t_enc = self._hps.max_enc_steps
        self._hps1 = self._hps.replace(batch_size=1)
        # prefill stage buckets — the micro-batcher's exact list (ONE
        # parser, config.parse_bucket_spec), so the two serving modes
        # route articles to identical encoder shapes
        self._buckets = parse_bucket_spec(self._hps.serve_buckets,
                                          self._hps.max_enc_steps)
        self._state = None  # lazy: first pack pays the init compile
        self._active = np.zeros(slots, dtype=bool)
        self._obs = obs.registry_for(self._hps)
        # ---- paged resident state (ISSUE 20) ----
        # resolve_arena_pages > 0 switches the engine to the paged
        # kernel set: enc-axis resident leaves pool into a shared
        # decode_enc_block-row page arena, each admission allocates
        # ceil(true_len/block) pages, and the per-slot page-table rows
        # ride into the kernels as traced DATA.  Same four compile
        # sites, same warm-set budget — paging changes the memory
        # story, never the compile story.
        self._block = resolve_enc_block(self._hps)
        self._b_max = -(-self._hps.max_enc_steps // self._block)
        self._page_bytes = 0
        if self._hps.serve_arena_pages > 0 or self._hps.serve_arena_mb > 0:
            self._page_bytes = beam_search.paged_page_bytes(
                decoder._params_snapshot()[0], self._hps)
        self._arena_pages = resolve_arena_pages(self._hps,
                                                self._page_bytes or None)
        self.paged = self._arena_pages > 0
        self._arena: Optional[PageArena] = (
            PageArena(self._arena_pages) if self.paged else None)
        # scratch-filled page table; row i mirrors slot i's allocation
        self._table = np.full((slots, self._b_max), self._arena_pages,
                              np.int32)
        self._page_rows: Dict[int, np.ndarray] = {}
        # commit the compile-once warm set to the compile ledger
        # (obs/profile.py, ISSUE 16): exactly one compile per decode
        # kernel (idx/occupancy/valid-lengths all traced) and one
        # prefill per serve bucket — growth beyond these budgets is a
        # compile storm (flight dump + /alerts), not just a failed test
        self._prof = profile_lib.profiler_for(self._obs)
        for kernel in ("decode/init_slots_jit", "decode/pack_slot_jit",
                       "decode/step_slots_jit", "decode/unpack_slot_jit"):
            self._prof.set_compile_budget(kernel, 1)
        self._prof.set_compile_budget("decode/prefill_jit",
                                      len(self._buckets))
        self._priced_buckets: set = set()
        if getattr(self._hps, "profile_analytic", False):
            # price the slot chunk ONCE for the divergence sentinel
            # (the helper AOT-compiles; profile.py runs it off-thread)
            chunk_hps, chunk = self._hps, self.chunk
            self._prof.register_cost(
                "serve/dispatch", f"slot_chunk{chunk}",
                lambda: __import__("__graft_entry__").decode_step_cost(
                    chunk_hps, path="slot", chunk=chunk))
        self._registry = None
        # (source params tree, its registry-placed copy): holding the
        # source object keeps its id live, so the identity check below
        # can never false-hit on a recycled address after a hot-swap
        self._placed_params: Optional[Tuple[Any, Any]] = None
        hps = self._hps
        if hps.dp * hps.tp * hps.sp > 1:
            if slots % hps.dp != 0:
                raise ValueError(
                    f"continuous serving shards resident slots over dp: "
                    f"dp={hps.dp} must divide serve slots={slots}")
            # the decoder already built the mesh plan under the same
            # condition — engine and micro-batch search share ONE
            # mesh/registry by construction
            self._registry = decoder._mesh_plan.registry

    @property
    def params_fingerprint(self) -> str:
        """The owning decoder's active-params fingerprint — the
        continuous path's cache-key surface (one decoder, one
        fingerprint, both serve modes; SERVING.md "Front door")."""
        return self._dec.params_fingerprint

    def _params(self):
        """The decoder's params snapshot, placed against the registry's
        param specs on a mesh (cached per swapped-in params object, so
        a checkpoint hot-swap re-places once, not per chunk)."""
        params, _ = self._dec._params_snapshot()
        if self._registry is None:
            return params
        if self._placed_params is None or self._placed_params[0] is not params:
            self._placed_params = (params,
                                   self._registry.shard_params(params))
        return self._placed_params[1]

    def _pin_state(self, state):
        """Pin the resident state to the registry's slots-over-dp specs
        (a no-op transfer when the layout already matches)."""
        if self._registry is None:
            return state
        import jax

        reg = self._registry
        return jax.device_put(
            state, reg.shardings(reg.slot_state_specs(state)))

    def _jitted(self, site, fn, *args, key="", **kw):
        """Run a slot kernel through the shared compile ledger
        (obs/profile.py, ISSUE 16): the jit-cache hit/miss telemetry
        this method used to hand-roll, plus per-site compile events so
        'no per-request recompiles' is runtime-monitored — growth past
        the committed warm-set budget is a compile storm."""
        return profile_lib.compiled_call(self._obs, site, fn, *args,
                                         key=key, **kw)

    def _ensure_state(self, params) -> None:
        if self._state is not None:
            return
        zero = {
            "enc_batch": np.zeros((self.slots, self._t_enc), np.int32),
            "enc_lens": np.zeros((self.slots,), np.int32),
            "enc_padding_mask": np.zeros((self.slots, self._t_enc),
                                         np.float32),
            "enc_batch_extend_vocab": np.zeros((self.slots, self._t_enc),
                                               np.int32),
        }
        if self._registry is not None:
            import jax

            reg = self._registry
            specs = reg.slot_batch_specs()
            zero = {k: jax.device_put(v, reg.named(specs[k]))
                    for k, v in zero.items()}
        if self.paged:
            self._state = self._pin_state(
                self._jitted("decode/init_slots_jit",
                             beam_search.init_slots_paged_jit, params,
                             self._hps, zero, self._arena_pages))
        else:
            self._state = self._pin_state(
                self._jitted("decode/init_slots_jit",
                             beam_search.init_slots_jit, params,
                             self._hps, zero))

    def _register_prefill_cost(self, bucket: int) -> None:
        """Queue analytic pricing of one prefill bucket for the
        divergence sentinel (first use per bucket; gated on
        hps.profile_analytic because prefill_cost AOT-compiles)."""
        if not getattr(self._hps, "profile_analytic", False) \
                or bucket in self._priced_buckets:
            return
        self._priced_buckets.add(bucket)
        hps = self._hps
        self._prof.register_cost(
            "serve/prefill", bucket,
            lambda: __import__("__graft_entry__").prefill_cost(hps, bucket))

    def prefill(self, example) -> PrefilledArticle:
        """The PREFILL stage for one SummaryExample (ISSUE 11): encoder
        + cross-attention cache at the article's bucket shape — one
        prefill_jit compile per bucket, cost scaling with the bucket —
        returning the padded, valid-length-stamped handle pack()
        scatters into a slot.  Safe to run while other articles are
        resident (the scheduler overlaps prefill with decode ticks)."""
        params = self._params()
        bucket = bucket_for(self._buckets, example.enc_len)
        batch = Batch([example], self._hps1, self._dec._vocab,
                      enc_steps=bucket)
        arrays = {k: v for k, v in batch.as_arrays().items()
                  if k.startswith("enc_")}
        self._register_prefill_cost(bucket)
        pre = self._jitted("decode/prefill_jit", beam_search.prefill_jit,
                           params, self._hps, arrays, key=bucket)
        if self._registry is not None:
            import jax

            reg = self._registry
            pre = jax.device_put(
                pre, reg.shardings(reg.prefill_state_specs(pre)))
        return PrefilledArticle(example=example, state=pre, bucket=bucket)

    def pages_needed(self, item) -> int:
        """Arena pages one admission consumes: ceil(true_len / block),
        read from the HOST-side example length (never the device
        array — pack is a TS002 hot path).  0 when paging is off."""
        if not self.paged:
            return 0
        enc_len = min(int(item.example.enc_len if isinstance(
            item, PrefilledArticle) else item.enc_len),
            self._hps.max_enc_steps)
        return max(1, -(-enc_len // self._block))

    def free_pages(self) -> int:
        """Free arena pages (for the batcher's admit-by-free-pages
        check); paging off reports the arena as bottomless."""
        if not self.paged:
            return 1 << 30
        return self._arena.free_pages

    def arena_stats(self) -> Optional[Dict[str, float]]:
        """Arena occupancy snapshot for the serve metrics/bench
        evidence fields; None when paging is off.  Pure host counters —
        no device sync."""
        if not self.paged:
            return None
        a = self._arena
        return {"capacity": a.capacity, "free": a.free_pages,
                "in_use": a.pages_in_use, "fill": a.fill}

    def resident_bytes_per_slot(self) -> float:
        """Mean resident HBM bytes one resident actually consumes —
        the ISSUE 20 evidence figure.  Dense engine: the static
        state-bytes / slots (every slot owns worst-case width whether
        occupied or not).  Paged engine: the dense (non-pooled) per-slot
        share plus the IN-USE pages' bytes averaged over current
        residents — array metadata and host counters only, no sync."""
        if self._state is None:
            return 0.0
        import jax

        leaves = jax.tree_util.tree_leaves(self._state)
        total = float(sum(x.nbytes for x in leaves))
        if not self.paged:
            return total / self.slots
        pools = list(self._state.enc_pages) + [self._state.ext_pool,
                                               self._state.attn_pool]
        dense = total - float(sum(x.nbytes for x in pools))
        n_active = max(1, int(self._active.sum()))
        return (dense / self.slots
                + self._arena.pages_in_use * self._page_bytes / n_active)

    def pack(self, idx: int, item) -> None:
        """Admit one prefilled article (or a raw SummaryExample, which
        is prefilled inline) into slot `idx` (must be free).

        Paged engine: allocates the admission's pages first — a typed
        ArenaExhaustedError propagates to the batcher BEFORE any device
        state changes (requeue, never a wrong decode), and a pack
        failure after allocation frees the pages (no leak)."""
        if self._active[idx]:
            raise AssertionError(f"slot {idx} is already resident")
        if not isinstance(item, PrefilledArticle):
            item = self.prefill(item)
        params = self._params()
        self._ensure_state(params)
        if self.paged:
            need = self.pages_needed(item)
            ids = self._arena.alloc(need)  # may raise ArenaExhaustedError
            row = np.full(self._b_max, self._arena_pages, np.int32)
            row[:need] = ids
            try:
                self._state = self._pin_state(
                    self._jitted("decode/pack_slot_jit",
                                 beam_search.pack_slot_paged_jit, params,
                                 self._hps, self._state, idx, item.state,
                                 row))
            except BaseException:
                self._arena.free(ids)
                raise
            self._table[idx] = row
            self._page_rows[idx] = ids
        else:
            self._state = self._pin_state(
                self._jitted("decode/pack_slot_jit",
                             beam_search.pack_slot_jit, params,
                             self._hps, self._state, idx, item.state))
        self._active[idx] = True

    def step(self) -> List[int]:
        """One chunk for every resident slot; returns the slot indices
        whose search finished (ready to unpack)."""
        if not self._active.any():
            return []
        params = self._params()
        # chunk-level span: tick-scoped, not request-scoped (a chunk
        # serves every resident at once, so there is no single parent
        # trace) — a request's timeline correlates with these spans by
        # timestamp via its slot/tick lifecycle events, not by trace_id
        with obs.spans.span(self._obs, "decode/slot_chunk",
                            active=int(self._active.sum())):
            if self.paged:
                self._state, finished = self._jitted(
                    "decode/step_slots_jit",
                    beam_search.step_slots_paged_jit, params, self._hps,
                    self._state, self._active, self._table, self.chunk)
            else:
                self._state, finished = self._jitted(
                    "decode/step_slots_jit", beam_search.step_slots_jit,
                    params, self._hps, self._state, self._active,
                    self.chunk)
            self._state = self._pin_state(self._state)
            # the one sanctioned chunk-boundary sync: the host scheduler
            # needs the finished mask to retire and refill slots
            return [int(i) for i in np.nonzero(np.asarray(finished))[0]]

    def unpack(self, idx: int, example) -> DecodedResult:
        """Retire slot `idx`: finalize its hypothesis and free the slot.
        `example` is the SummaryExample packed into it (uuid/reference/
        OOV map travel with the request, not the device state)."""
        if not self._active[idx]:
            raise AssertionError(f"slot {idx} is not resident")
        if self.paged:
            out = self._jitted("decode/unpack_slot_jit",
                               beam_search.unpack_slot_paged_jit,
                               self._hps, self._state, idx,
                               self._table[idx])
            self._free_slot_pages(idx)
        else:
            out = self._jitted("decode/unpack_slot_jit",
                               beam_search.unpack_slot_jit, self._hps,
                               self._state, idx)
        self._active[idx] = False
        res = self._dec._make_result(
            np.asarray(out.tokens), int(out.length),
            np.asarray(out.attn_dists), np.asarray(out.p_gens),
            uuid=example.uuid, article=example.original_article,
            reference=example.reference,
            abstract_sents=example.original_abstract_sents,
            art_oovs=example.article_oovs)
        self._dec._c_requests.inc()
        self._dec._c_beams.inc()
        self._dec._c_tokens.inc(len(res.decoded_words))
        return res

    def _free_slot_pages(self, idx: int) -> None:
        """Return slot `idx`'s pages to the arena and point its table
        row back at the scratch page.  Safe after the unpack dispatch:
        jit outputs are fresh buffers, so a later pack's scatter into
        the recycled pages cannot race the retiring gather."""
        ids = self._page_rows.pop(idx, None)
        if ids is not None:
            self._arena.free(ids)
            self._table[idx] = self._arena_pages

    def release(self, idx: int) -> None:
        """Free slot `idx` WITHOUT unpacking (deadline eviction): the
        stale state is masked out until the next pack overwrites it,
        and a paged slot's pages go straight back to the arena."""
        if self.paged:
            self._free_slot_pages(idx)
        self._active[idx] = False

    def active_count(self) -> int:
        return int(self._active.sum())

    def cache_sizes(self) -> Dict[str, int]:
        """Jit-cache entry counts of the four decode kernels plus the
        bucketed prefill — the 'bounded compile cache' evidence (tests
        assert the decode kernels never grow after warmup and prefill
        stays at one entry per serve bucket).  In paged mode the four
        kernels are the *_paged variants (ISSUE 20) — counting the
        kernels this engine actually dispatches is what makes the pin
        meaningful (the dense caches would sit frozen regardless)."""
        if self.paged:
            kernels = (beam_search.init_slots_paged_jit,
                       beam_search.prefill_jit,
                       beam_search.pack_slot_paged_jit,
                       beam_search.step_slots_paged_jit,
                       beam_search.unpack_slot_paged_jit)
        else:
            kernels = (beam_search.init_slots_jit, beam_search.prefill_jit,
                       beam_search.pack_slot_jit, beam_search.step_slots_jit,
                       beam_search.unpack_slot_jit)
        out: Dict[str, int] = {}
        for fn in kernels:
            try:
                out[fn.__wrapped__.__name__] = fn._cache_size()
            except Exception:  # tslint: disable=TS005 — private jax API; absent on some builds
                pass
        return out
