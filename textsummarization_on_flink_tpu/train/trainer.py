"""Training/eval loops: jitted train step, NaN watchdog, metrics, timing.

Rebuilds the reference's training stack TPU-first:
  * `run_training` / `FlinkTrainer.train` (run_summarization.py:212-244,
    train.py:89-125) -> `Trainer.train`: per-step loss + wall-clock logging,
    summaries, non-finite-loss watchdog (train.py:107-108), optional
    step limit (StopAtStepHook parity, train.py:70-72).
  * `run_eval` (run_summarization.py:247-292) -> `Evaluator.run`:
    exponentially-smoothed running-average loss (decay .99, clipped at 12,
    run_summarization.py:105-129) driving best-model selection.
  * The TF1 PS/worker + MonitoredTrainingSession machinery is replaced by
    a single jitted step (sharded over the mesh in parallel/ for DP).

Summaries are JSON-lines under `<log_root>/<exp_name>/<job>/events.jsonl`
(the reference's TensorBoard scalars, minus the TF dependency).
"""

from __future__ import annotations

import functools
import json
import logging
import os
import time
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.obs import flightrec
from textsummarization_on_flink_tpu.obs import http as obs_http
from textsummarization_on_flink_tpu.obs import profile as profile_lib
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.models import get_family
from textsummarization_on_flink_tpu.resilience import faultinject
from textsummarization_on_flink_tpu.train import optim

log = logging.getLogger(__name__)

Array = jax.Array
PyTree = Any


class TrainState(NamedTuple):
    params: PyTree
    opt_state: optim.AdagradState
    step: Array  # scalar int32 global step


class StepMetrics(NamedTuple):
    loss: Array
    coverage_loss: Array
    total_loss: Array
    global_norm: Array


def opt_state_dtype(hps: HParams):
    """Adagrad accumulator storage dtype for this config (None = follow
    the param dtype; jnp.bfloat16 under --opt_state_dtype=bfloat16)."""
    if getattr(hps, "opt_state_dtype", "float32") == "bfloat16":
        return jnp.bfloat16
    return None


def init_train_state(hps: HParams, vsize: int, seed: Optional[int] = None,
                     params: Optional[PyTree] = None) -> TrainState:
    if params is None:
        params = get_family(hps.model_family).init_params(
            hps, vsize, jax.random.PRNGKey(seed if seed is not None else hps.seed))
    return TrainState(params=params,
                      opt_state=optim.adagrad_init(params,
                                                   hps.adagrad_init_acc,
                                                   dtype=opt_state_dtype(hps)),
                      step=jnp.zeros((), jnp.int32))


def cast_opt_state(hps: HParams, state: TrainState) -> TrainState:
    """Align a state's accumulator dtype with --opt_state_dtype (e.g. a
    checkpoint restored as f32 — npz cannot hold bf16, so the
    checkpointer widens on save — resuming a bf16-state run)."""
    dtype = opt_state_dtype(hps) or jnp.float32
    acc = state.opt_state.accumulators
    leaves = jax.tree_util.tree_leaves(acc)
    if all(getattr(x, "dtype", None) == dtype for x in leaves):
        return state
    return state._replace(opt_state=optim.AdagradState(
        accumulators=jax.tree_util.tree_map(
            lambda a: jnp.asarray(a).astype(dtype), acc)))


def make_loss_fn(hps: HParams):
    """(params, arrays) -> (objective, TrainOutput) — the ONE definition
    of the training objective, shared by make_train_step and the
    explicit-collective sharded step (parallel/mesh.py) so the two can
    never drift."""
    family = get_family(hps.model_family)

    def loss_fn(params: PyTree, arrays: Dict[str, Array]):
        out = family.forward_train(params, hps, arrays)
        # minimize total_loss when coverage is on (model.py:291)
        objective = out.total_loss if hps.coverage else out.loss
        return objective, out

    return loss_fn


def make_grad_fn(hps: HParams) -> Callable:
    """(params, arrays) -> (grads, (loss, coverage_loss, total_loss)) —
    the default gradient computation: one jax.grad of the shared loss
    objective, reductions left to XLA (under pjit the partitioner
    inserts the dp gradient psum in the grads' own dtype).  The sharded
    step builder (parallel/mesh.py) substitutes a registry-driven
    variant when the grad wire dtype is annotated."""
    loss_fn_ = make_loss_fn(hps)

    def grad_fn(params: PyTree, arrays: Dict[str, Array]):
        grads, out = jax.grad(
            lambda p: loss_fn_(p, arrays), has_aux=True)(params)
        return grads, (out.loss, out.coverage_loss, out.total_loss)

    return grad_fn


def make_train_step(hps: HParams, grad_fn: Optional[Callable] = None,
                    ) -> Callable[[TrainState, Dict[str, Array]],
                                  Tuple[TrainState, StepMetrics]]:
    """Build the pure train-step function (jit it, or pjit via parallel/).

    The step BODY (clip -> Adagrad -> state/metrics) exists only here:
    every path — single-device jit, the pjit mesh step, and the
    bf16-wire collective variant — shares it and differs solely in the
    `grad_fn` that produces (grads, scalar losses) (ISSUE 8: one jitted
    step, layout and wire dtype decided by the sharding registry)."""

    grad_fn_ = grad_fn if grad_fn is not None else make_grad_fn(hps)

    def train_step(state: TrainState, arrays: Dict[str, Array]):
        grads, (loss, cov_loss, total) = grad_fn_(state.params, arrays)
        grads, gnorm = optim.clip_by_global_norm(grads, hps.max_grad_norm)
        new_params, new_opt = optim.adagrad_update(
            grads, state.opt_state, state.params, hps.lr)
        new_state = TrainState(params=new_params, opt_state=new_opt,
                               step=state.step + 1)
        metrics = StepMetrics(loss=loss, coverage_loss=cov_loss,
                              total_loss=total, global_norm=gnorm)
        return new_state, metrics

    return train_step


def make_eval_step(hps: HParams):
    family = get_family(hps.model_family)

    def eval_step(params: PyTree, arrays: Dict[str, Array]) -> StepMetrics:
        out = family.forward_train(params, hps, arrays)
        return StepMetrics(loss=out.loss, coverage_loss=out.coverage_loss,
                           total_loss=out.total_loss,
                           global_norm=jnp.zeros(()))
    return eval_step


def calc_running_avg_loss(loss: float, running_avg_loss: float,
                          decay: float = 0.99) -> float:
    """Early-stopping smoother (run_summarization.py:105-129)."""
    if running_avg_loss == 0:
        running_avg_loss = loss
    else:
        running_avg_loss = running_avg_loss * decay + (1 - decay) * loss
    return min(running_avg_loss, 12)


class SummaryWriter:
    """JSONL scalar summaries (TensorBoard-writer stand-in).  Default
    cadence flushes every record; flush_every=k buffers k records per
    flush (the reference flushes every 100 steps,
    run_summarization.py:242-244).  Multi-host: only the chief writes
    (is_chief MonitoredTrainingSession role, train.py:74-81); other hosts
    get a no-op writer so a shared log_root sees one record per step.

    Robustness (ISSUE 1 satellite 2): a deleted/rotated log directory
    must never crash the train loop — the writer recreates the directory
    and reopens the file; a persistent failure drops the record and
    counts it in the ``train/summary_write_errors`` obs counter."""

    def __init__(self, directory: str, flush_every: int = 1,
                 registry: Optional[obs.Registry] = None):
        from textsummarization_on_flink_tpu.parallel import distributed

        self._dir = directory
        self._flush_every = max(int(flush_every), 1)
        self._unflushed = 0
        self._chief = distributed.is_chief()
        self._f = None
        reg = registry if registry is not None else obs.registry()
        self._write_errors = reg.counter("train/summary_write_errors")
        if self._chief:
            self._path = os.path.join(directory, "events.jsonl")
            self._open()

    def _open(self) -> bool:
        try:
            os.makedirs(self._dir, exist_ok=True)
            self._f = open(self._path, "a", encoding="utf-8")
            return True
        except OSError:
            self._f = None
            return False

    def scalars(self, step: int, **values: float) -> None:
        if not self._chief:
            return
        rec = {"step": int(step)}
        rec.update({k: float(v) for k, v in values.items()})
        line = json.dumps(rec) + "\n"
        # POSIX keeps writes to an unlinked file succeeding silently, so
        # a rotated log dir must be detected by path, not by exception.
        # Stat at batch start and just before a flush — not on every
        # buffered write — and count buffered records the rotation ate.
        if (self._f is not None
                and (self._unflushed == 0
                     or self._unflushed + 1 >= self._flush_every)
                and not os.path.exists(self._path)):
            self._drop_buffered()
        for _attempt in (0, 1):
            if self._f is None and not self._open():
                continue
            try:
                self._f.write(line)
                self._unflushed += 1
                if self._unflushed >= self._flush_every:
                    self._f.flush()
                    self._unflushed = 0
                return
            except (OSError, ValueError):  # rotated dir / closed file
                self._drop_buffered()
        self._write_errors.inc()
        log.warning("summary write failed (rotated log dir?); record for "
                    "step %d dropped", step)

    def _drop_buffered(self) -> None:
        """Close a dead file handle; any buffered-but-unflushed records
        went into the unlinked inode, so count them as write errors
        rather than losing them silently."""
        if self._unflushed:
            self._write_errors.inc(self._unflushed)
            log.warning("summary log dir rotated; %d buffered records "
                        "lost", self._unflushed)
        try:
            self._f.close()
        except (OSError, ValueError):  # double-close / rotated-dir close
            pass
        self._f = None
        self._unflushed = 0

    def flush(self) -> None:
        if self._f is not None:
            try:
                self._f.flush()
                self._unflushed = 0
            except (OSError, ValueError):
                self._write_errors.inc()

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except (OSError, ValueError):
                pass
            self._f = None


class NonFiniteLossError(RuntimeError):
    """Raised by the NaN/Inf watchdog (train.py:107-108 parity)."""


class NanLossError(NonFiniteLossError):
    """Divergence recovery exhausted its budgets (RESILIENCE.md): the
    watchdog skipped ``hps.nan_skip_steps`` batches and rolled back
    ``hps.nan_max_rollbacks`` times, and the loss still went non-finite.
    A ``NonFiniteLossError`` subclass so pre-existing watchdog handlers
    keep working."""


class _DivergenceRecovery:
    """Armed NaN/Inf recovery state (hps.nan_skip_steps > 0 or
    hps.nan_max_rollbacks > 0).

    Recovery ladder on a non-finite dispatch:
      1. SKIP — discard the dispatch (params revert to the pre-step
         state; the trainer runs without buffer donation when armed, so
         the reference is still live) and try the next batch, up to
         ``nan_skip_steps`` consecutive skips; any finite dispatch
         resets the budget.
      2. ROLLBACK — restore the last good checkpoint (or, without a
         checkpointer / before the first save, the host-side last-good
         snapshot) and cut the LR by ``nan_lr_cut``; up to
         ``nan_max_rollbacks`` times.
      3. RAISE — ``NanLossError``.

    Counters: ``resilience/train/nan_skips_total``,
    ``resilience/train/rollbacks_total``; gauge
    ``resilience/train/lr_scale``.
    """

    def __init__(self, hps: HParams, checkpointer: Any,
                 registry: obs.Registry, initial_state: "TrainState"):
        self.hps = hps
        self.checkpointer = checkpointer
        self.skips_left = hps.nan_skip_steps
        self.rollbacks_left = hps.nan_max_rollbacks
        self.lr_scale = 1.0
        self._c_skips = registry.counter("resilience/train/nan_skips_total")
        self._c_rollbacks = registry.counter(
            "resilience/train/rollbacks_total")
        self._g_lr_scale = registry.gauge("resilience/train/lr_scale")
        self._g_lr_scale.set(1.0)
        # rollback fallback when no checkpoint exists yet (the initial
        # state is always good); refreshed only when there is no
        # checkpointer to restore from, and then only every
        # SNAPSHOT_EVERY good dispatches — a per-step device_get of the
        # full state (params + optimizer moments) would serialize every
        # dispatch, and rollback semantics only promise "a known-good
        # earlier state", not the newest one
        self.snapshot = jax.device_get(initial_state)
        self._good_since_snapshot = 0

    SNAPSHOT_EVERY = 10

    def note_good(self, state: "TrainState") -> None:
        self.skips_left = self.hps.nan_skip_steps  # consecutive budget
        if self.checkpointer is None:
            self._good_since_snapshot += 1
            if self._good_since_snapshot >= self.SNAPSHOT_EVERY:
                self.snapshot = jax.device_get(state)
                self._good_since_snapshot = 0

    def next_action(self) -> str:
        if self.skips_left > 0:
            return "skip"
        if self.rollbacks_left > 0:
            return "rollback"
        return "raise"

    def take_skip(self) -> None:
        self.skips_left -= 1
        self._c_skips.inc()

    def take_rollback(self) -> "TrainState":
        """Consume one rollback: cut the LR and return the state to
        resume from (host-side leaves; the next dispatch re-transfers)."""
        self.rollbacks_left -= 1
        self.skips_left = self.hps.nan_skip_steps
        self.lr_scale *= self.hps.nan_lr_cut
        self._g_lr_scale.set(self.lr_scale)
        self._c_rollbacks.inc()
        restored = (self.checkpointer.restore()
                    if self.checkpointer is not None else None)
        return restored if restored is not None else self.snapshot


class PrefetchError(RuntimeError):
    """The DevicePrefetcher's worker thread failed; the original cause
    is chained (``raise ... from``).  Typed so consumers can tell an
    input-pipeline death from any other RuntimeError (ISSUE 1 satellite
    1) — and a RuntimeError subclass so pre-existing handlers keep
    working."""


class DevicePrefetcher:
    """Double-buffered host->device feed (SURVEY §2.5 'intra-op
    threading' row: the reference keeps the feed queue full with 16+4
    batcher threads; on TPU the remaining stall is the synchronous H2D
    copy, hidden here by transferring batch N+1 while N computes).

    Wraps any batcher; `next_batch()` returns (batch, device_arrays).

    Failure contract: a worker-thread error surfaces on the NEXT
    `next_batch()` call as a typed PrefetchError — the consumer polls
    rather than parking forever in a blocking get, so a pump death can
    never strand the train loop on a drained queue.

    Telemetry (obs/): `train/prefetch_queue_depth` gauge (sampled per
    consumer pull), `train/prefetch_starvation_total` (pulls after the
    first delivered batch that found the queue empty — the device
    out-ran the input pipeline; cold-start warmup before batch one is
    expected latency, not starvation, and is not counted),
    `train/prefetch_errors_total`, `train/prefetch_batches_total`.
    """

    def __init__(self, batcher: Any, transfer: Callable[[Dict], Dict],
                 depth: int = 2,
                 registry: Optional[obs.Registry] = None):
        import queue as queue_lib
        import threading

        self._batcher = batcher
        self._transfer = transfer
        self._q: Any = queue_lib.Queue(maxsize=max(depth, 1))
        self._done = object()
        self._stopped = threading.Event()
        self._delivered_any = False
        self.error: Optional[BaseException] = None
        reg = registry if registry is not None else obs.registry()
        self._g_depth = reg.gauge("train/prefetch_queue_depth")
        self._c_starved = reg.counter("train/prefetch_starvation_total")
        self._c_errors = reg.counter("train/prefetch_errors_total")
        self._c_batches = reg.counter("train/prefetch_batches_total")
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        import queue as queue_lib

        while not self._stopped.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue_lib.Full:
                continue
        return False

    def _pump(self) -> None:
        try:
            while not self._stopped.is_set():
                batch = self._batcher.next_batch()
                if batch is None:
                    break
                # the device_put happens HERE, ahead of the consumer
                if not self._put((batch, self._transfer(batch.as_arrays()))):
                    return  # stopped while parked on a full queue
        except BaseException as e:  # re-raised by the consumer
            self.error = e
            self._c_errors.inc()
            log.exception("device prefetcher failed")
        finally:
            self._put(self._done)

    def next_batch(self):
        import queue as queue_lib

        self._g_depth.set(self._q.qsize())
        starved = False
        while True:
            try:
                item = self._q.get(timeout=0.2)
                break
            except queue_lib.Empty:
                # the consumer is ahead of the pump: either genuine
                # input starvation (counted once per pull, and only
                # after the first batch — cold-start warmup is not the
                # device out-running the pipeline) or the pump died
                # before parking its _done sentinel — surface the typed
                # error instead of waiting forever
                if not starved and self._delivered_any:
                    starved = True
                    self._c_starved.inc()
                if self.error is not None and self._q.empty():
                    raise PrefetchError(
                        "input pipeline failed mid-training") from self.error
        if item is self._done:
            if self.error is not None:
                raise PrefetchError(
                    "input pipeline failed mid-training") from self.error
            return None
        self._c_batches.inc()
        self._delivered_any = True
        return item

    def stop(self) -> None:
        """Reap the pump thread (a limit/abort exit must not keep draining
        the shared source)."""
        self._stopped.set()
        self._thread.join(timeout=10.0)


class Trainer:
    """Single-host training driver.

    batcher: anything with next_batch() -> Batch|None (data/batcher.py or a
    streaming bridge).  checkpointer: optional, saves every
    `checkpoint_secs` (Supervisor save_model_secs=60 parity,
    run_summarization.py:198) and at the end.
    """

    def __init__(self, hps: HParams, vsize: int, batcher: Any,
                 state: Optional[TrainState] = None,
                 checkpointer: Optional[Any] = None,
                 checkpoint_secs: float = 60.0,
                 checkpoint_steps: int = 0,
                 metrics_every: int = 0,
                 train_dir: Optional[str] = None,
                 step_fn: Optional[Callable] = None):
        self.hps = hps
        self.batcher = batcher
        # Metrics cadence: fetching metrics is a blocking D2H sync that
        # serializes dispatch (and defeats DevicePrefetcher), so losses
        # are fetched/logged/NaN-checked in windows of `metrics_every`
        # steps.  0 = auto: per-step under --debug (exact watchdog, the
        # reference's per-step logging), every 10 steps otherwise.  The
        # summary JSONL still gets one record per step either way.
        self.metrics_every = (metrics_every
                              or getattr(hps, "metrics_every", 0)
                              or (1 if hps.debug else 10))
        # Checkpoint cadence: `checkpoint_steps` (kwarg or the
        # --checkpoint_steps flag) triggers on STEP boundaries — REQUIRED
        # on multi-host, where save() is collective and a wall-clock
        # trigger would fire at different steps per host (hard guard in
        # _train_loop).  Without it, single-host keeps the reference's
        # save_model_secs wall-clock behavior (run_summarization.py:198).
        # With steps_per_dispatch=k, the wall-clock check (and the
        # profiler start/stop) runs only at dispatch boundaries, so both
        # quantize to k steps — same cadence note as metrics_every above.
        self.checkpoint_steps = (checkpoint_steps
                                 or getattr(hps, "checkpoint_steps", 0))
        self.state = state if state is not None else init_train_state(hps, vsize)
        # a restored checkpoint always holds f32 accumulators (npz cannot
        # represent bf16); re-narrow when this run stores them in bf16
        self.state = cast_opt_state(hps, self.state)
        # k train steps per device dispatch (an on-device scan over k
        # stacked batches — config.py steps_per_dispatch).  --debug pins
        # k=1: the exact per-step watchdog needs per-dispatch fetches.
        self.steps_per_dispatch = max(
            1 if hps.debug else getattr(hps, "steps_per_dispatch", 1), 1)
        self._multi_step_cache: Dict[int, Callable] = {}
        self.checkpointer = checkpointer
        self.checkpoint_secs = checkpoint_secs
        self.train_dir = train_dir or os.path.join(
            hps.log_root or ".", hps.exp_name or "exp", "train")
        # observability (OBSERVABILITY.md `train/` namespace); hps.obs
        # False runs this job dark via the null registry
        self._obs = obs.registry_for(hps)
        self._m_step_time = self._obs.histogram("train/step_time_seconds")
        self._m_host_wait = self._obs.histogram("train/host_wait_seconds")
        self._m_fetch = self._obs.histogram("train/metrics_fetch_seconds")
        self._c_steps = self._obs.counter("train/steps_total")
        self._c_examples = self._obs.counter("train/examples_total")
        self._c_nan = self._obs.counter("train/nan_watchdog_total")
        self._c_dump_errors = self._obs.counter("train/nan_dump_errors_total")
        # same gauge instance the DevicePrefetcher writes (get-or-create
        # by name): read per flushed step into flight-recorder frames
        self._g_prefetch = self._obs.gauge("train/prefetch_queue_depth")
        # run-scoped trace root (ISSUE 9): metrics-flush spans carry the
        # run's trace_id so one training run's spans link in events.jsonl
        # the way one serve request's do
        self._trace = (obs.TraceContext.new() if self._obs.enabled
                       else None)
        # the phase ledger (obs/profile.py, ISSUE 16): the loop's
        # host-wait/step-dispatch/metrics-flush/checkpoint sub-phases
        # bracketed by a per-round wall; dark jobs get the null
        # profiler (constant-return, no per-step allocation)
        self._prof = profile_lib.profiler_for(self._obs)
        if getattr(hps, "profile_analytic", False):
            # analytic train-step pricing for the divergence sentinel —
            # AOT cost analysis runs off the hot path (provider thread)
            cost_hps = hps
            self._prof.register_cost(
                "train/step_dispatch", "step",
                lambda: __import__("__graft_entry__").train_step_cost(
                    cost_hps))
        # failure flight recorder: per-step frames ring in memory and
        # dump to <train_dir>/flight_<reason>.jsonl when the NaN
        # watchdog / divergence recovery fires (OBSERVABILITY.md)
        if self._obs.enabled and getattr(hps, "flight_frames", 0) > 0:
            flightrec.install_flight_recorder(
                self._obs, self.train_dir, capacity=hps.flight_frames)
        # live exposition plane (off unless TS_OBS_HTTP /
        # HParams(obs_http_port) enables it; one server per process)
        obs_http.maybe_serve(self._obs, hps)
        # resilience (RESILIENCE.md): the fault plan is resolved ONCE so
        # the per-point RNG streams stay deterministic across the run;
        # unarmed jobs hold the null singleton (fire() is `return False`)
        self._faults = faultinject.plan_for(hps)
        armed = hps.nan_skip_steps > 0 or hps.nan_max_rollbacks > 0
        self._recovery: Optional[_DivergenceRecovery] = None
        if armed:
            if hps.dp * hps.tp * hps.sp > 1 or jax.process_count() > 1:
                raise ValueError(
                    "divergence recovery (nan_skip_steps/nan_max_rollbacks) "
                    "is single-host, default-mesh only: a skip must revert "
                    "to the pre-step state, which the sharded/multi-host "
                    "collective step donates away")
            if step_fn is not None:
                raise ValueError(
                    "divergence recovery requires the trainer-built train "
                    "step (LR cuts rebuild it); drop the custom step_fn or "
                    "disarm nan_skip_steps/nan_max_rollbacks")
            self._recovery = _DivergenceRecovery(
                hps, checkpointer, self._obs, self.state)
        self.writer = SummaryWriter(
            self.train_dir,
            flush_every=getattr(hps, "summary_flush_every", 1),
            registry=self._obs)
        # TS_OBS_EVENTS=1: stream finished spans into the SAME
        # events.jsonl the scalar summaries use (the unified format,
        # OBSERVABILITY.md) through the bounded background flusher.
        # Opt-in: every sink is a daemon thread, and most Trainer
        # constructions (tests, short fits) don't want one.
        if (self._obs.enabled and self._obs.event_sink is None
                and os.environ.get("TS_OBS_EVENTS", "").lower()
                in ("1", "on", "true", "yes")):
            from textsummarization_on_flink_tpu.obs import export as obs_export

            obs_export.install_event_sink(self._obs, self.train_dir)
        self._shard_batch: Optional[Callable] = None
        if step_fn is None:
            if hps.dp * hps.tp * hps.sp > 1:
                # SPMD over the (dp, tp, sp) mesh: the sharded step IS the
                # distributed backend (parallel/mesh.py) — XLA inserts the
                # dp-axis gradient psum and tp/sp collectives.
                from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib

                mesh_lib.validate_divisibility(hps, self.state.params)
                plan = mesh_lib.make_mesh(hps)
                self.state = mesh_lib.shard_train_state(plan, self.state)
                if jax.process_count() > 1:
                    # Each host's batcher must feed ITS shard of the
                    # global batch: batch_size/process_count rows per
                    # host (configure the batcher with the LOCAL size;
                    # hps.batch_size stays the global batch).
                    self._shard_batch = mesh_lib.make_host_local_transfer(
                        plan, hps.batch_size, label="train")
                else:
                    self._shard_batch = functools.partial(
                        mesh_lib.shard_batch, plan)
                step_fn = mesh_lib.make_sharded_train_step(
                    plan, state=self.state)
            else:
                step_fn = self._build_step_fn()
        self._step_fn = step_fn

    def _build_step_fn(self) -> Callable:
        """The single-device jitted step.  Unarmed: donates the input
        state (lowest memory).  Armed divergence recovery: NO donation —
        a skip reverts to the pre-step state, so its buffers must
        survive the dispatch — and the LR carries the rollback cut."""
        hps = self.hps
        if self._recovery is not None:
            if self._recovery.lr_scale != 1.0:
                hps = hps.replace(lr=hps.lr * self._recovery.lr_scale)
            return jax.jit(make_train_step(hps))
        return jax.jit(make_train_step(hps), donate_argnums=0)

    def train(self, num_steps: Optional[int] = None) -> TrainState:
        """Run until num_steps (hps.num_steps when None; 0 = until the
        batcher is exhausted).

        Profiling (SURVEY §5.1): the reference logs per-step wall clock
        only; here HParams(profile_dir=...) — or the legacy
        TS_PROFILE_DIR env fallback — captures a JAX/XLA profiler trace
        of steps 2-7 (post-compilation) for TensorBoard's trace viewer,
        and the capture window lands in the profiler ledger as a
        `profiler_capture` note (ISSUE 16) so /profile shows WHEN a
        trace was taken alongside the phase table it annotates.
        """
        limit = self.hps.num_steps if num_steps is None else num_steps
        # checkpoint cadence is a DURATION: monotonic, never wall clock
        # (TS003 — an NTP slew/suspend must not skip or double a save)
        last_ckpt = time.monotonic()
        # HParam wins over the env fallback: a config-driven run must
        # not be silently redirected by ambient shell state
        profile_dir = (getattr(self.hps, "profile_dir", "")
                       or os.environ.get("TS_PROFILE_DIR"))
        # anchor to the first step of THIS run (may resume past step 2)
        profile_start = int(self.state.step) + 2
        profile_stop = profile_start + 5
        try:
            return self._train_loop(limit, last_ckpt, profile_dir,
                                    profile_start, profile_stop)
        finally:
            # a finished (or aborted) run is not a WEDGED run: retire
            # the loop heartbeat so /healthz doesn't 503 a process that
            # trained to completion and moved on (e.g. train -> serve)
            obs_http.retire_heartbeat(self._obs, "train/loop")
            if profile_dir:
                try:  # finalize a trace left open by an exception/NaN abort
                    jax.profiler.stop_trace()
                except RuntimeError:
                    pass  # no trace active

    def _train_loop(self, limit, last_ckpt, profile_dir, profile_start,
                    profile_stop) -> TrainState:
        multihost = jax.process_count() > 1
        if multihost and not limit:
            # Collective ops (train step, checkpoint gather) must stay in
            # lockstep; per-host data shards exhaust at different steps,
            # so an until-exhausted run cannot be multi-host-safe.
            raise ValueError(
                "multi-host training requires an explicit num_steps limit "
                "(per-host streams may end at different steps, desyncing "
                "collectives)")
        if multihost and getattr(self.hps, "single_pass", False):
            # Even with a limit, a finite per-host stream can end early on
            # one host while the others still issue collective steps —
            # that host would then enter the collective checkpoint save
            # and hang the job.
            raise ValueError(
                "multi-host training cannot use single_pass (finite "
                "per-host streams end at different steps, desyncing "
                "collectives); stream an infinite shuffled pass instead")
        if multihost and self.checkpointer is not None \
                and self.checkpoint_steps <= 0:
            # A wall-clock cadence would fire at different steps on
            # different hosts and desync the collective save; no silent
            # reinterpretation of checkpoint_secs as steps (VERDICT r3).
            raise ValueError(
                "multi-host training with a checkpointer requires an "
                "explicit checkpoint_steps cadence (--checkpoint_steps "
                "or Trainer(checkpoint_steps=...)); the wall-clock "
                "checkpoint_secs cadence is single-host only")
        transfer = self._shard_batch if self._shard_batch is not None \
            else jax.device_put
        # depth covers one full multi-step pull plus a batch in flight,
        # so a k-batch dispatch never starves on the depth-2 default
        prefetcher = DevicePrefetcher(
            self.batcher, transfer,
            depth=max(2, self.steps_per_dispatch + 1),
            registry=self._obs)
        try:
            return self._train_steps(limit, last_ckpt, profile_dir,
                                     profile_start, profile_stop,
                                     prefetcher, multihost)
        finally:
            prefetcher.stop()

    def _multi_step(self, k: int) -> Callable:
        """k train steps as ONE dispatch: an on-device lax.scan over k
        batches stacked on a leading axis (steps_per_dispatch — the TPU
        steps_per_execution pattern; k-fold fewer host round trips).
        Numerically identical to k sequential dispatches."""
        fn = self._multi_step_cache.get(k)
        if fn is None:
            step_fn = self._step_fn

            def multi(state, stacked):
                return jax.lax.scan(
                    lambda s, arrays: step_fn(s, arrays), state, stacked)

            # armed recovery: the pre-dispatch state must survive a skip
            fn = (jax.jit(multi) if self._recovery is not None
                  else jax.jit(multi, donate_argnums=0))
            self._multi_step_cache[k] = fn
        return fn

    def _flush_metrics(self, pending, window_dt) -> None:
        """Fetch a window of device-resident metrics in one D2H transfer,
        log + summarize each step, and run the NaN watchdog
        (train.py:107-108 parity, detection deferred <= metrics_every
        steps unless --debug pins the window to 1).

        pending: [(first_step, n_steps, metrics, arrays|None)] — metrics
        leaves are scalars when n_steps == 1, [n_steps]-vectors from the
        multi-step scan otherwise."""
        if not pending:
            return
        p0 = self._prof.start()
        # the fetch is a blocking D2H sync — its cost is exactly the
        # dispatch-serialization price the windowing amortizes, so it is
        # measured (train/metrics_fetch_seconds) rather than guessed
        t_fetch = time.perf_counter()
        with obs.spans.span(self._obs, "train/metrics_flush",
                            parent=self._trace, step=pending[0][0]):
            fetched = jax.device_get([m for _, _, m, _ in pending])
        self._m_fetch.observe(time.perf_counter() - t_fetch)
        total = sum(n for _, n, _, _ in pending)
        step_time = window_dt / max(total, 1)
        for _ in range(total):  # window average, one sample per step
            self._m_step_time.observe(step_time)
        log.info("seconds for training step: %.3f (avg over %d)",
                 step_time, total)
        prefetch_depth = self._g_prefetch.value
        for (step0, n, _, arrays), m in zip(pending, fetched):
            for i in range(n):
                step = step0 + i
                pick = (lambda x: x) if n == 1 else (lambda x: x[i])
                loss = float(pick(m.loss))
                log.info("loss: %f", loss)
                scalars = dict(loss=loss,
                               total_loss=float(pick(m.total_loss)),
                               global_norm=float(pick(m.global_norm)),
                               step_time=step_time)
                if self.hps.coverage:
                    cl = float(pick(m.coverage_loss))
                    log.info("coverage_loss: %f", cl)
                    scalars["coverage_loss"] = cl
                # per-step flight frame: what the NaN post-mortem reads
                # (a finite-or-not loss ships either way — the LAST
                # frames before a blowup are the interesting ones)
                flightrec.record(
                    self._obs, "train_step", step=step, loss=loss,
                    global_norm=float(pick(m.global_norm)),
                    step_time=round(step_time, 6),
                    prefetch_depth=prefetch_depth)
                if not np.isfinite(loss):
                    self._c_nan.inc()
                    self._dump_nan_batch(step, arrays)
                    flightrec.trigger(self._obs, "train_nan", step=step)
                    # worst case: the bad step opens a window that only
                    # flushes at >= metrics_every steps, reached in whole
                    # k-step dispatches — so up to metrics_every + k - 2
                    # steps can run past it (ADVICE r3)
                    lag = max(max(self.metrics_every, 1)
                              + self.steps_per_dispatch - 2, 0)
                    raise NonFiniteLossError(
                        f"Loss is not finite. Stopping. "
                        f"(step {step}, loss {loss}; detection is "
                        f"windowed — up to {lag} "
                        f"optimizer steps may have run past the first "
                        f"bad one; --debug pins the window to 1 for "
                        f"step-exact detection)")
                self.writer.scalars(step + 1, **scalars)
        self._prof.end("train/metrics_flush", p0)

    def _dump_nan_batch(self, step: int, arrays) -> None:
        """--debug: persist the batch that produced a non-finite loss
        (the reference wires tfdbg's has_inf_or_nan filter here,
        run_summarization.py:216-218)."""
        if not self.hps.debug or arrays is None:
            return
        path = os.path.join(self.train_dir, f"nan_batch_step{step}.npz")
        try:
            os.makedirs(self.train_dir, exist_ok=True)
            np.savez(path, **{k: np.asarray(v) for k, v in arrays.items()})
            log.error("non-finite loss at step %d; offending batch "
                      "dumped to %s", step, path)
        except Exception:  # the watchdog error must still propagate
            self._c_dump_errors.inc()
            log.exception("failed to dump NaN batch")

    def _recover(self, step: int) -> bool:
        """Armed divergence handling for one non-finite dispatch.

        Returns True when the run can continue (the offending dispatch
        was discarded; ``self.state`` is the state to resume from) and
        False when the skip AND rollback budgets are exhausted — the
        caller raises NanLossError.
        """
        rec = self._recovery
        action = rec.next_action()
        if action == "skip":
            rec.take_skip()
            log.warning(
                "non-finite loss at step %d: skipping the batch "
                "(%d consecutive skips left before rollback)",
                step, rec.skips_left)
            return True
        if action == "rollback":
            restored = rec.take_rollback()
            # the post-mortem moment: the frames BEFORE this rollback are
            # what "what did the last N steps look like?" asks about
            flightrec.trigger(self._obs, "nan_rollback", step=step,
                              rollbacks_left=rec.rollbacks_left)
            self.state = restored
            # the LR cut changes the step function: rebuild and drop the
            # multi-step cache (both re-jit; a rollback is rare enough
            # that the recompile is noise)
            self._step_fn = self._build_step_fn()
            self._multi_step_cache.clear()
            log.warning(
                "non-finite loss at step %d: rolled back to step %d with "
                "lr scale %.3g (%d rollbacks left)",
                step, int(np.asarray(restored.step)), rec.lr_scale,
                rec.rollbacks_left)
            return True
        return False

    def _train_steps(self, limit, last_ckpt, profile_dir, profile_start,
                     profile_stop, prefetcher, multihost) -> TrainState:
        profiling = False
        # multihost + checkpointer guarantees checkpoint_steps > 0 (the
        # hard guard in _train_loop); an explicit step cadence also wins
        # on single-host, else the wall-clock checkpoint_secs cadence
        # below applies
        checkpoint_steps = self.checkpoint_steps
        flush_every = max(self.metrics_every, 1)
        # metrics stay on device until flushed; keeping the (tiny) input
        # arrays alongside lets --debug dump the exact offending batch
        # (--debug forces steps_per_dispatch=1, so arrays are per-step)
        pending = []  # [(first_step, n_steps, device_metrics, arrays)]
        pending_steps = 0
        window_t0 = time.monotonic()
        # ONE device sync to learn the resume step; from here the counter
        # is tracked host-side (+n per dispatch) so the loop never blocks
        # on state.step and dispatch can run ahead of the device
        step = int(self.state.step)
        profile_done = False  # one-shot: never restart a finished trace
        exhausted = False
        while not exhausted:
            # trainer-loop heartbeat for /healthz (obs/http.py): one beat
            # per dispatch; 3x the shared period of silence — a wedged
            # input pipeline, a hung collective — marks the loop
            # degraded (LOOP_HEARTBEAT_PERIOD carries the
            # compile/checkpoint-tolerance rationale)
            obs_http.heartbeat(self._obs, "train/loop",
                               period=obs_http.LOOP_HEARTBEAT_PERIOD)
            if limit and step >= limit:
                break
            # per-round wall bracket (obs/profile.py, ISSUE 16): the
            # sub-phases below sum toward it, and the gap is the loop's
            # unattributed overhead (stacking, bookkeeping)
            w0 = self._prof.start()
            # k batches per dispatch (steps_per_dispatch), clipped to the
            # remaining step budget so the limit stays exact
            k = self.steps_per_dispatch
            if limit:
                k = min(k, limit - step)
            items = []
            t_wait = time.perf_counter()
            p0 = self._prof.start()
            while len(items) < k:
                item = prefetcher.next_batch()
                if item is None:
                    exhausted = True
                    break
                items.append(item)
            # host-wait: time the loop spent blocked on the input side
            # while the device sat idle (dispatch itself is async)
            self._m_host_wait.observe(time.perf_counter() - t_wait)
            self._prof.end("train/host_wait", p0)
            if exhausted and (multihost and (limit == 0 or step + len(items)
                                             < limit)):
                raise RuntimeError(
                    f"batcher exhausted at step {step + len(items)} before "
                    f"the num_steps={limit} limit on a multi-host run; "
                    f"other hosts may still be issuing collectives — "
                    f"aborting instead of desyncing")
            if not items:
                log.info("batcher exhausted; stopping training at step %d",
                         step)
                break
            if profile_dir and not profiling and not profile_done \
                    and step >= profile_start:
                self._flush_metrics(pending, time.monotonic() - window_t0)
                pending = []
                pending_steps = 0
                jax.profiler.start_trace(profile_dir)
                profiling = True
                window_t0 = time.monotonic()
                # the capture's opening edge in the profiler ledger
                # (ISSUE 16): /profile names the step range a trace
                # covers without grepping logs
                self._prof.note("profiler_capture", dir=str(profile_dir),
                                start_step=profile_start,
                                stop_step=profile_stop)
                log.info("profiler trace started -> %s", profile_dir)
            n = len(items)
            p0 = self._prof.start()
            try:
                if n == 1:
                    _, arrays = items[0]
                    new_state, metrics = self._step_fn(self.state, arrays)
                else:
                    # stack on device: k tiny int/float batch arrays gain
                    # a leading scan axis (bytes ~ k x the batch, trivial
                    # next to one dispatch round trip)
                    arrays = jax.tree_util.tree_map(
                        lambda *xs: jnp.stack(xs),
                        *[a for _, a in items])
                    new_state, metrics = self._multi_step(n)(
                        self.state, arrays)
                    arrays = None
            except FloatingPointError as e:
                # jax_debug_nans (--debug, which pins n=1) raises inside
                # the step with the op-level location; still dump the
                # offending batch and surface the watchdog error type
                self._c_nan.inc()
                self._dump_nan_batch(step, arrays)
                flightrec.trigger(self._obs, "train_nan", step=step)
                if self._recovery is not None:
                    # the step never completed, so self.state is still
                    # the pre-dispatch state — skip/rollback from it
                    if self._recover(step):
                        # recovery path, not the per-step path: one sync
                        # to learn the resume step
                        step = int(np.asarray(self.state.step))  # tslint: disable=TS002
                        continue
                    raise NanLossError(
                        f"Loss is not finite and divergence recovery is "
                        f"exhausted. Stopping. (step {step}; "
                        f"jax_debug_nans trace above)") from e
                raise NonFiniteLossError(
                    f"Loss is not finite. Stopping. (step {step}; "
                    f"jax_debug_nans trace above)") from e
            # dispatch-submit time (async under jax: device compute
            # overlaps with the host loop; the blocking D2H fetches are
            # the metrics-flush phase, not this one)
            dt = self._prof.end("train/step_dispatch", p0)
            self._prof.observe_dispatch("train/step_dispatch", "step", dt)
            injected = self._faults.fire("train.step_nan")
            if self._recovery is not None:
                # armed: one D2H metrics sync per dispatch — poisoned
                # state must never outlive the dispatch that made it (the
                # documented cost of arming, config.py nan_skip_steps)
                fetched = jax.device_get(metrics)  # tslint: disable=TS002
                finite = bool(np.all(np.isfinite(np.asarray(fetched.loss))))  # tslint: disable=TS002 — host data
                if injected or not finite:
                    self._c_nan.inc()
                    self._dump_nan_batch(step, arrays)
                    flightrec.trigger(self._obs, "train_nan", step=step,
                                      injected=bool(injected))
                    # new_state is discarded; self.state (pre-dispatch,
                    # never donated when armed) remains the live params
                    if self._recover(step):
                        step = int(np.asarray(self.state.step))  # tslint: disable=TS002
                        continue
                    raise NanLossError(
                        f"Loss is not finite and divergence recovery is "
                        f"exhausted. Stopping. (step {step}"
                        f"{'; injected train.step_nan' if injected else ''})")
                self.state = new_state
                self._recovery.note_good(new_state)
                metrics = fetched  # flush below reuses the fetched copy
            else:
                # the dispatch itself completed: publish its state BEFORE
                # any injected raise, so self.state never points at
                # buffers the donated step already consumed (an on-error
                # handler may still save it)
                self.state = new_state
                if injected:
                    self._c_nan.inc()
                    flightrec.trigger(self._obs, "train_nan", step=step,
                                      injected=True)
                    raise NonFiniteLossError(
                        f"injected train.step_nan fault at step {step} "
                        f"(divergence recovery unarmed: nan_skip_steps and "
                        f"nan_max_rollbacks are 0)")
            pending.append((step, n, metrics,
                            arrays if self.hps.debug else None))
            prev_step = step
            step += n
            pending_steps += n
            self._c_steps.inc(n)
            self._c_examples.inc(n * self.hps.batch_size)
            if pending_steps >= flush_every or self._recovery is not None:
                self._flush_metrics(pending, time.monotonic() - window_t0)
                pending = []
                pending_steps = 0
                window_t0 = time.monotonic()
            if profiling and step > profile_stop:
                # the finalize edge gets its own span so one capture is
                # one linkable event in events.jsonl (trace_summary.py
                # lanes show the trace window next to the step spans)
                with obs.spans.span(self._obs, "train/profiler_capture",
                                    parent=self._trace,
                                    start_step=profile_start,
                                    stop_step=profile_stop):
                    jax.profiler.stop_trace()
                profiling = False
                profile_done = True
                log.info("profiler trace written to %s", profile_dir)
            if self.checkpointer is not None:
                if checkpoint_steps > 0:
                    # crossed a cadence boundary this dispatch — identical
                    # arithmetic on every host, so saves stay collective
                    # even when k does not divide checkpoint_steps
                    due = (step // checkpoint_steps
                           ) != (prev_step // checkpoint_steps)
                else:
                    due = time.monotonic() - last_ckpt >= self.checkpoint_secs
                if due:
                    # the save fetches state anyway; fold the metrics
                    # flush into the same sync point
                    self._flush_metrics(pending, time.monotonic() - window_t0)
                    pending = []
                    pending_steps = 0
                    p0 = self._prof.start()
                    self.checkpointer.save(self.state)
                    self._prof.end("train/checkpoint", p0)
                    last_ckpt = time.monotonic()
                    window_t0 = time.monotonic()
            self._prof.end_wall("train/round", w0)
        self._flush_metrics(pending, time.monotonic() - window_t0)
        if profiling:
            jax.profiler.stop_trace()
        if self.checkpointer is not None:
            self.checkpointer.save(self.state)
        return self.state


class Evaluator:
    """Eval loop with running-average loss + best-model hook
    (run_summarization.py:247-292)."""

    def __init__(self, hps: HParams, vsize: int, batcher: Any,
                 eval_dir: Optional[str] = None,
                 best_saver: Optional[Callable[[PyTree, float, int], None]] = None):
        self.hps = hps
        self.batcher = batcher
        self.eval_dir = eval_dir or os.path.join(
            hps.log_root or ".", hps.exp_name or "exp", "eval")
        self._obs = obs.registry_for(hps)
        self._m_eval_batch = self._obs.histogram("train/eval_batch_seconds")
        self._c_eval_batches = self._obs.counter("train/eval_batches_total")
        self.writer = SummaryWriter(
            self.eval_dir,
            flush_every=getattr(hps, "summary_flush_every", 1),
            registry=self._obs)
        self.best_saver = best_saver
        self.running_avg_loss = 0.0
        self.best_loss: Optional[float] = None
        self._shard_batch: Optional[Callable] = None
        self._mesh_plan = None
        if hps.dp * hps.tp * hps.sp > 1:  # same auto-mesh rule as Trainer
            from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib

            self._mesh_plan = mesh_lib.make_mesh(hps)
            if jax.process_count() > 1:  # same per-host-shard rule as Trainer
                self._shard_batch = mesh_lib.make_host_local_transfer(
                    self._mesh_plan, hps.batch_size, label="eval")
            else:
                self._shard_batch = functools.partial(
                    mesh_lib.shard_batch, self._mesh_plan)
            self._eval_fn = None  # built lazily per params structure
        else:
            self._eval_fn = jax.jit(make_eval_step(hps))

    def run(self, params: PyTree, step: int, max_batches: int = 0) -> float:
        """Evaluate batches (all, or max_batches); returns running avg loss."""
        n = 0
        while True:
            batch = self.batcher.next_batch()
            if batch is None:
                break
            t0 = time.monotonic()
            arrays = batch.as_arrays()
            if self._shard_batch is not None:
                arrays = self._shard_batch(arrays)
            if self._eval_fn is None:  # mesh path: build for THIS params
                from textsummarization_on_flink_tpu.parallel import (
                    mesh as mesh_lib,
                )

                mesh_lib.validate_divisibility(self.hps, params)
                self._eval_fn = mesh_lib.make_sharded_eval_step(
                    self._mesh_plan, params=params)
            metrics = self._eval_fn(params, arrays)
            loss = float(metrics.total_loss if self.hps.coverage else metrics.loss)
            self._m_eval_batch.observe(time.monotonic() - t0)
            self._c_eval_batches.inc()
            log.info("seconds for eval batch: %.3f  loss: %f",
                     time.monotonic() - t0, loss)
            if not np.isfinite(loss):
                raise NonFiniteLossError("Eval loss is not finite.")
            self.running_avg_loss = calc_running_avg_loss(
                loss, self.running_avg_loss)
            self.writer.scalars(step, eval_loss=loss,
                                running_avg_loss=self.running_avg_loss)
            # best-model check PER eval iteration, inside the loop — the
            # reference saves whenever the smoothed loss improves after
            # each eval step (run_summarization.py:281-292), not once per
            # evaluation session
            if self.best_loss is None or self.running_avg_loss < self.best_loss:
                log.info("Found new best model with %.3f running_avg_loss. "
                         "Saving...", self.running_avg_loss)
                if self.best_saver is not None:
                    self.best_saver(params, self.running_avg_loss, step)
                self.best_loss = self.running_avg_loss
            n += 1
            if max_batches and n >= max_batches:
                break
        return self.running_avg_loss
