"""Training: optimizer, jitted/pjitted train step, loops, eval."""
