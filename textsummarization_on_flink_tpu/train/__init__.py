"""Training: optimizer, jitted/pjitted train step, loops, eval, and
sequence-level draft distillation (train/distill.py — the narrow
speculative draft trained from the frozen full model through the same
loss head and step body as from-scratch training)."""
