"""Sequence-level distillation of the narrow AAN draft (ISSUE 12;
PERF.md "Distilled narrow draft").

The narrow draft (``draft_hidden`` < hidden_dim + factored vocab head,
models/avg_attention.py) is what makes speculation pay on FLOPs — but
its decoder has no full-model counterpart to map from, so it must be
TRAINED.  This module trains it to imitate the FROZEN full model:

  * the teacher decodes each batch ONCE through the existing greedy
    tier (``beam_size=1`` beam search — bitwise the program the serving
    ladder's greedy tier and the spec verifier's acceptance test run),
  * the teacher's emitted stream becomes the teacher-forced
    (dec_batch, target_batch, dec_padding_mask) triple — extended-vocab
    ids stay in the TARGETS (the pointer mixture scores them against
    the article) and feed back UNK-mapped as inputs, the decoder's own
    feed-back rule,
  * the draft trains on that triple through the SHARED
    ``transformer.train_output_tail`` loss head with the standard
    clip -> Adagrad step body (``trainer.make_train_step``), so the
    distillation objective and the from-scratch objective are one code
    path.

This is sequence-level distillation in the Kim & Rush sense: the
student fits the teacher's MODE (its greedy output) — exactly the
sequence the spec verifier compares proposals against — so the loss
directly optimizes the acceptance rate the BYTE_BUDGET.json spec gate
pins (held-out floor enforced in tier-1).

Checkpointing: the draft's TrainState rides the standard
``checkpoint.Checkpointer`` format in its own directory, PLUS a
``teacher.json`` sidecar carrying a content fingerprint of the frozen
teacher — ``restore()`` refuses a draft checkpoint whose teacher does
not match the one in hand, so the (full, draft) pair can never be
silently mismatched across a save/restore cycle.  At serve time the
distilled draft injects via ``BeamSearchDecoder(draft_params=...)``
(``load_distilled_draft``); mapped (``spec_draft="map"``) drafts keep
re-deriving on checkpoint hot-swap under the decoder's params lock.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams, derive_draft_hps
from textsummarization_on_flink_tpu.data.vocab import START_ID, UNK_ID
from textsummarization_on_flink_tpu.train import trainer as trainer_lib

log = logging.getLogger(__name__)

TEACHER_SIDECAR = "teacher.json"


def teacher_fingerprint(full_params: Any) -> str:
    """Content fingerprint of the frozen teacher: sha256 over every
    leaf's bytes in deterministic (flattened-name) order.  Cheap at
    any committed scale (one pass over ~100 MB) and exact — two
    teachers collide only if they are byte-identical.  Delegates to
    the ONE scheme (``checkpoint.checkpointer.content_fingerprint``,
    shared with the serve layer's summary-cache key since ISSUE 14) so
    sidecar and cache fingerprints can never drift."""
    from textsummarization_on_flink_tpu.checkpoint import checkpointer as ck

    return ck.content_fingerprint(full_params)


def teacher_arrays(full_params: Any, hps: HParams,
                   arrays: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """One batch's distillation triple: greedy-decode the articles with
    the frozen teacher (the greedy tier's exact program) and lay the
    emitted stream out teacher-forced.  Targets keep extended-vocab
    ids (the pointer loss scores copies); inputs are the targets
    shifted right behind START and UNK-mapped (the feed-back rule)."""
    from textsummarization_on_flink_tpu.decode import beam_search

    thps = hps.replace(beam_size=1, mode="decode")
    enc = {k: v for k, v in arrays.items() if k.startswith("enc_")}
    out = beam_search.run_beam_search(full_params, thps, enc)
    B = enc["enc_batch"].shape[0]
    T = hps.max_dec_steps
    dec = np.zeros((B, T), np.int32)
    tgt = np.zeros((B, T), np.int32)
    mask = np.zeros((B, T), np.float32)
    tokens = np.asarray(out.tokens)
    lengths = np.asarray(out.length)
    for b in range(B):
        n = min(int(lengths[b]) - 1, T)  # generated tokens (past START)
        if n <= 0:
            continue
        gen = tokens[b, 1:1 + n].astype(np.int64)
        inputs = np.concatenate(([START_ID], gen[:n - 1]))
        dec[b, :n] = np.where(inputs >= hps.vocab_size, UNK_ID, inputs)
        tgt[b, :n] = gen
        mask[b, :n] = 1.0
    return {**enc, "dec_batch": dec, "target_batch": tgt,
            "dec_padding_mask": mask}


def acceptance_rate(full_params: Any, draft_params: Any, hps: HParams,
                    arrays: Dict[str, np.ndarray]) -> float:
    """Measured accept fraction (accepted / drafted) of one spec-decode
    pass — the distillation quality number the BYTE_BUDGET.json spec
    gate floors on its held-out synthetic set."""
    from textsummarization_on_flink_tpu.decode import speculative

    out = speculative.run_spec_decode(full_params, draft_params, hps,
                                      arrays)
    drafted = int(out.drafted.sum())
    return int(out.accepted.sum()) / drafted if drafted else 0.0


def load_distilled_draft(train_dir: str,
                         full_params: Optional[Any] = None) -> Any:
    """Draft params from the newest checkpoint in a DistillTrainer
    directory, verifying the teacher sidecar against ``full_params``
    when given — the serve-side loader for
    ``BeamSearchDecoder(draft_params=...)``."""
    from textsummarization_on_flink_tpu.checkpoint import checkpointer as ck

    path, flat = ck.load_ckpt(train_dir, max_retries=0)
    state = ck.arrays_to_state(flat)
    if full_params is not None:
        _check_teacher(train_dir, teacher_fingerprint(full_params), path)
    return state.params


def _check_teacher(train_dir: str, fingerprint: str, ckpt_path: str) -> None:
    sidecar = os.path.join(train_dir, TEACHER_SIDECAR)
    try:
        with open(sidecar, encoding="utf-8") as f:
            want = json.load(f)["teacher_sha"]
    except (OSError, KeyError, ValueError):
        return  # pre-sidecar dir: nothing to verify against
    if want != fingerprint:
        raise ValueError(
            f"distilled draft at {ckpt_path} was trained against teacher "
            f"{want}, not the full model in hand ({fingerprint}) — a "
            f"mismatched (full, draft) pair silently tanks acceptance; "
            f"re-distill or load the matching teacher checkpoint")


class DistillTrainer:
    """Single-host distillation driver for the narrow draft.

    ``hps`` is the FULL model's config (the draft shape derives through
    ``config.derive_draft_hps`` — the one resolver, so the trained
    draft is exactly the shape the decoder will build); ``batcher`` is
    any ``next_batch() -> Batch | None`` source of ARTICLES (the
    abstracts are ignored — the teacher writes the targets).

    ``cache_teacher=True`` memoizes the teacher triple per batch
    OBJECT — the epoch-over-a-fixed-set recipe (tests, smokes): the
    teacher decodes each batch once, later epochs pay only the draft
    step.
    """

    def __init__(self, hps: HParams, vsize: int, batcher: Any,
                 full_params: Any,
                 state: Optional[trainer_lib.TrainState] = None,
                 checkpointer: Optional[Any] = None,
                 checkpoint_secs: float = 60.0,
                 metrics_every: int = 0,
                 cache_teacher: bool = False,
                 seed: Optional[int] = None):
        self.hps = hps
        self.dhps = derive_draft_hps(hps).replace(mode="train")
        self.batcher = batcher
        self.full_params = full_params
        self.checkpointer = checkpointer
        self.checkpoint_secs = checkpoint_secs
        self.metrics_every = (metrics_every
                              or getattr(hps, "metrics_every", 0) or 10)
        self._teacher_sha = teacher_fingerprint(full_params)
        restored = None
        if state is None and checkpointer is not None:
            restored = checkpointer.restore()
            if restored is not None:
                _check_teacher(checkpointer.directory, self._teacher_sha,
                               "restored checkpoint")
                restored = trainer_lib.cast_opt_state(self.dhps, restored)
        if state is not None:
            self.state = state
        elif restored is not None:
            self.state = restored
        else:
            self.state = trainer_lib.init_train_state(
                self.dhps, vsize,
                seed=seed if seed is not None else hps.seed)
        # the shared step BODY (clip -> Adagrad) over the draft family's
        # forward through the shared loss head — ONE objective code path
        # with from-scratch training (trainer.make_grad_fn(dhps))
        self._step_fn = jax.jit(trainer_lib.make_train_step(self.dhps))
        self._cache: Optional[Dict[int, Any]] = {} if cache_teacher else None
        self._obs = obs.registry_for(hps)
        self._c_steps = self._obs.counter("train/distill_steps_total")
        self._g_loss = self._obs.gauge("train/distill_loss")
        self._m_teacher = self._obs.histogram(
            "train/distill_teacher_seconds")

    def draft_params(self) -> Any:
        return self.state.params

    def _teacher_arrays(self, batch: Any) -> Dict[str, np.ndarray]:
        if self._cache is not None and id(batch) in self._cache:
            # the cache holds (batch, arrays): the batch ref pins the
            # object alive, so an id() can never be recycled under us
            return self._cache[id(batch)][1]
        t0 = time.monotonic()
        arrays = teacher_arrays(self.full_params, self.hps,
                                batch.as_arrays())
        self._m_teacher.observe(time.monotonic() - t0)
        if self._cache is not None:
            self._cache[id(batch)] = (batch, arrays)
        return arrays

    def _save(self) -> None:
        if self.checkpointer is None:
            return
        self.checkpointer.save(self.state)
        sidecar = os.path.join(self.checkpointer.directory, TEACHER_SIDECAR)
        with open(sidecar, "w", encoding="utf-8") as f:
            json.dump({"teacher_sha": self._teacher_sha}, f)

    def distill(self, num_steps: int) -> trainer_lib.TrainState:
        """Run ``num_steps`` distillation steps (or until the batcher
        exhausts); saves the draft checkpoint + teacher sidecar at the
        cadence and at the end."""
        state = self._distill_steps(num_steps)
        self._save()
        return state

    def _flush_metrics(self, pending) -> None:
        """One D2H fetch for a window of device-resident losses (the
        Trainer's windowed-watchdog discipline: detection deferred at
        most metrics_every steps)."""
        if not pending:
            return
        fetched = jax.device_get([m for _, m in pending])
        for (step, _), m in zip(pending, fetched):
            loss = float(m.loss)
            self._g_loss.set(loss)
            log.info("distill step %d loss %f", step, loss)
            if not np.isfinite(loss):
                raise trainer_lib.NonFiniteLossError(
                    f"distillation loss is not finite at step {step}")

    def _distill_steps(self, limit: int) -> trainer_lib.TrainState:
        last_ckpt = time.monotonic()
        pending = []
        step = int(self.state.step)
        start = step
        while not limit or step - start < limit:
            batch = self.batcher.next_batch()
            if batch is None:
                break
            arrays = self._teacher_arrays(batch)
            self.state, metrics = self._step_fn(self.state, arrays)
            step += 1
            pending.append((step, metrics))
            self._c_steps.inc()
            if len(pending) >= self.metrics_every:
                self._flush_metrics(pending)
                pending = []
            if self.checkpointer is not None and \
                    time.monotonic() - last_ckpt >= self.checkpoint_secs:
                self._flush_metrics(pending)
                pending = []
                self._save()
                last_ckpt = time.monotonic()
        self._flush_metrics(pending)
        return self.state
