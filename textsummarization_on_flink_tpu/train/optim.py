"""Adagrad + global-norm clipping, exact TF1 semantics.

The reference trains with `tf.train.AdagradOptimizer(lr,
initial_accumulator_value=0.1)` after `clip_by_global_norm(grads, 2.0)`
(model.py:288-305).  TF1 Adagrad (no epsilon):

    accum += g^2
    param -= lr * g / sqrt(accum)

optax's adagrad adds an eps inside the rsqrt, so we hand-roll the exact
update as an optax-style GradientTransformation.  The global-norm clip
matches tf.clip_by_global_norm: scale all grads by
min(1, max_norm / global_norm).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array
PyTree = Any


class AdagradState(NamedTuple):
    accumulators: PyTree


def global_norm(tree: PyTree) -> Array:
    return jnp.sqrt(sum(jnp.sum(jnp.square(x))
                        for x in jax.tree_util.tree_leaves(tree)))


def clip_by_global_norm(tree: PyTree, max_norm: float) -> Tuple[PyTree, Array]:
    """tf.clip_by_global_norm parity: returns (clipped, pre-clip norm)."""
    norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-30))
    return jax.tree_util.tree_map(lambda x: x * scale, tree), norm


def adagrad_init(params: PyTree, initial_accumulator_value: float,
                 dtype: Optional[Any] = None) -> AdagradState:
    """dtype=None stores the accumulator in each param's dtype (f32
    masters -> f32 state, the TF1 behavior); dtype=jnp.bfloat16 halves
    the optimizer state's HBM bytes (--opt_state_dtype=bfloat16) — the
    update math still runs in f32, see adagrad_update."""
    return AdagradState(accumulators=jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, initial_accumulator_value,
                           dtype or p.dtype), params))


def adagrad_update(grads: PyTree, state: AdagradState, params: PyTree,
                   lr: float) -> Tuple[PyTree, AdagradState]:
    """Returns (new_params, new_state).

    Storage-dtype-aware: the accumulator is widened to the param dtype
    (f32) before the g^2 add and the rsqrt, then rounded back to its
    storage dtype — so a bf16 accumulator (--opt_state_dtype=bfloat16)
    pays only HBM bytes, never f32 update precision within a step.  With
    an f32 accumulator the widen/narrow casts are no-ops and the update
    is bit-identical to the historical formula."""

    def wide_acc(a, g, p):
        return a.astype(p.dtype) + jnp.square(g)

    new_acc32 = jax.tree_util.tree_map(wide_acc, state.accumulators, grads,
                                       params)
    new_params = jax.tree_util.tree_map(
        lambda p, g, a: p - lr * g * jax.lax.rsqrt(a),
        params, grads, new_acc32)
    new_acc = jax.tree_util.tree_map(
        lambda a32, a_old: a32.astype(a_old.dtype),
        new_acc32, state.accumulators)
    return new_params, AdagradState(accumulators=new_acc)
