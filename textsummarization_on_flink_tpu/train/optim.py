"""Adagrad + global-norm clipping, exact TF1 semantics.

The reference trains with `tf.train.AdagradOptimizer(lr,
initial_accumulator_value=0.1)` after `clip_by_global_norm(grads, 2.0)`
(model.py:288-305).  TF1 Adagrad (no epsilon):

    accum += g^2
    param -= lr * g / sqrt(accum)

optax's adagrad adds an eps inside the rsqrt, so we hand-roll the exact
update as an optax-style GradientTransformation.  The global-norm clip
matches tf.clip_by_global_norm: scale all grads by
min(1, max_norm / global_norm).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array
PyTree = Any


class AdagradState(NamedTuple):
    accumulators: PyTree


def global_norm(tree: PyTree) -> Array:
    return jnp.sqrt(sum(jnp.sum(jnp.square(x))
                        for x in jax.tree_util.tree_leaves(tree)))


def clip_by_global_norm(tree: PyTree, max_norm: float) -> Tuple[PyTree, Array]:
    """tf.clip_by_global_norm parity: returns (clipped, pre-clip norm)."""
    norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-30))
    return jax.tree_util.tree_map(lambda x: x * scale, tree), norm


def adagrad_init(params: PyTree, initial_accumulator_value: float) -> AdagradState:
    return AdagradState(accumulators=jax.tree_util.tree_map(
        lambda p: jnp.full_like(p, initial_accumulator_value), params))


def adagrad_update(grads: PyTree, state: AdagradState, params: PyTree,
                   lr: float) -> Tuple[PyTree, AdagradState]:
    """Returns (new_params, new_state)."""
    new_acc = jax.tree_util.tree_map(
        lambda a, g: a + jnp.square(g), state.accumulators, grads)
    new_params = jax.tree_util.tree_map(
        lambda p, g, a: p - lr * g * jax.lax.rsqrt(a), params, grads, new_acc)
    return new_params, AdagradState(accumulators=new_acc)
