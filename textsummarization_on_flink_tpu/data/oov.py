"""Pointer-generator extended-vocabulary (in-article OOV) machinery.

Behavior parity with data.py:144-276 of the reference: in-article OOVs get
temporary ids vocab_size+0, vocab_size+1, ... in order of first appearance;
abstract words map to those temp ids when copyable, else UNK; output ids map
back to words through the per-article OOV list.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from textsummarization_on_flink_tpu.data.vocab import (
    SENTENCE_END,
    SENTENCE_START,
    UNKNOWN_TOKEN,
    Vocab,
)


def article2ids(article_words: Sequence[str], vocab: Vocab) -> Tuple[List[int], List[str]]:
    ids: List[int] = []
    oovs: List[str] = []
    unk_id = vocab.word2id(UNKNOWN_TOKEN)
    for w in article_words:
        i = vocab.word2id(w)
        if i == unk_id:
            if w not in oovs:
                oovs.append(w)
            ids.append(vocab.size() + oovs.index(w))
        else:
            ids.append(i)
    return ids, oovs


def abstract2ids(abstract_words: Sequence[str], vocab: Vocab,
                 article_oovs: Sequence[str]) -> List[int]:
    ids: List[int] = []
    unk_id = vocab.word2id(UNKNOWN_TOKEN)
    for w in abstract_words:
        i = vocab.word2id(w)
        if i == unk_id:
            if w in article_oovs:
                ids.append(vocab.size() + article_oovs.index(w))
            else:
                ids.append(unk_id)
        else:
            ids.append(i)
    return ids


def outputids2words(id_list: Sequence[int], vocab: Vocab,
                    article_oovs: Optional[Sequence[str]]) -> List[str]:
    words: List[str] = []
    for i in id_list:
        try:
            w = vocab.id2word(i)
        except ValueError:
            assert article_oovs is not None, (
                "Error: model produced a word ID that isn't in the vocabulary. "
                "This should not happen in baseline (no pointer-generator) mode")
            article_oov_idx = i - vocab.size()
            if article_oov_idx < 0 or article_oov_idx >= len(article_oovs):
                raise ValueError(
                    f"Error: model produced word ID {i} which corresponds to "
                    f"article OOV {article_oov_idx} but this example only has "
                    f"{len(article_oovs)} article OOVs")
            w = article_oovs[article_oov_idx]
        words.append(w)
    return words


def abstract2sents(abstract: str) -> List[str]:
    """Split '<s> ... </s>'-delimited abstract text into sentences."""
    cur = 0
    sents: List[str] = []
    while True:
        try:
            start_p = abstract.index(SENTENCE_START, cur)
            end_p = abstract.index(SENTENCE_END, start_p + 1)
            cur = end_p + len(SENTENCE_END)
            sents.append(abstract[start_p + len(SENTENCE_START):end_p])
        except ValueError:
            return sents


def show_art_oovs(article: str, vocab: Vocab) -> str:
    unk_id = vocab.word2id(UNKNOWN_TOKEN)
    words = article.split(" ")
    words = [f"__{w}__" if vocab.word2id(w) == unk_id else w for w in words]
    return " ".join(words)


def show_abs_oovs(abstract: str, vocab: Vocab,
                  article_oovs: Optional[Sequence[str]]) -> str:
    unk_id = vocab.word2id(UNKNOWN_TOKEN)
    new_words = []
    for w in abstract.split(" "):
        if vocab.word2id(w) == unk_id:
            if article_oovs is None:
                new_words.append(f"__{w}__")
            elif w in article_oovs:
                new_words.append(f"__{w}__")
            else:
                new_words.append(f"!!__{w}__!!")
        else:
            new_words.append(w)
    return " ".join(new_words)
