"""Data layer: vocab, tf.Example codec, chunk IO, OOV machinery, batching."""

from textsummarization_on_flink_tpu.data.vocab import (  # noqa: F401
    PAD_TOKEN,
    SENTENCE_END,
    SENTENCE_START,
    START_DECODING,
    STOP_DECODING,
    UNKNOWN_TOKEN,
    Vocab,
)
from textsummarization_on_flink_tpu.data.tfexample import (  # noqa: F401
    Example as TFExample,
)
from textsummarization_on_flink_tpu.data.oov import (  # noqa: F401
    abstract2ids,
    abstract2sents,
    article2ids,
    outputids2words,
    show_abs_oovs,
    show_art_oovs,
)
from textsummarization_on_flink_tpu.data.chunks import (  # noqa: F401
    example_generator,
    read_chunk_file,
    write_chunk_file,
)
