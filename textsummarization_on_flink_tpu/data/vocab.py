"""Vocabulary with the reference's exact special-token id assignment.

Behavior parity with /root/reference/src/main/python/pointer-generator/
data.py:26-105: specials [UNK]=0, [PAD]=1, [START]=2, [STOP]=3; vocab file
is "<word> <freq>" lines, most frequent first; malformed lines are skipped
with a warning; <s>/</s>/specials in the file are an error; duplicates are
an error; reading stops at max_size (0 = unlimited).
"""

from __future__ import annotations

import logging
from typing import Dict, Iterable, List, Optional

log = logging.getLogger(__name__)

SENTENCE_START = "<s>"
SENTENCE_END = "</s>"

PAD_TOKEN = "[PAD]"
UNKNOWN_TOKEN = "[UNK]"
START_DECODING = "[START]"
STOP_DECODING = "[STOP]"

_SPECIALS = (UNKNOWN_TOKEN, PAD_TOKEN, START_DECODING, STOP_DECODING)
_FORBIDDEN = (SENTENCE_START, SENTENCE_END) + _SPECIALS

UNK_ID = 0
PAD_ID = 1
START_ID = 2
STOP_ID = 3


class Vocab:
    """Word <-> id mapping (data.py:37-105 semantics)."""

    def __init__(self, vocab_file: Optional[str] = None, max_size: int = 0,
                 words: Optional[Iterable[str]] = None):
        """Build from a vocab file, or directly from an iterable of words
        (test convenience; words must not include specials)."""
        self._word_to_id: Dict[str, int] = {}
        self._id_to_word: Dict[int, str] = {}
        self._count = 0
        for w in _SPECIALS:
            self._word_to_id[w] = self._count
            self._id_to_word[self._count] = w
            self._count += 1

        if vocab_file is not None:
            with open(vocab_file, "r", encoding="utf-8") as f:
                for line in f:
                    pieces = line.split()
                    if len(pieces) != 2:
                        log.warning(
                            "incorrectly formatted line in vocabulary file: %r", line)
                        continue
                    self._add(pieces[0])
                    if max_size != 0 and self._count >= max_size:
                        log.info(
                            "max_size of vocab was specified as %i; we now have %i "
                            "words. Stopping reading.", max_size, self._count)
                        break
        if words is not None:
            for w in words:
                self._add(w)
                if max_size != 0 and self._count >= max_size:
                    break
        log.info("Finished constructing vocabulary of %i total words. "
                 "Last word added: %s", self._count, self._id_to_word[self._count - 1])

    def _add(self, w: str) -> None:
        if w in _FORBIDDEN:
            raise ValueError(
                f"<s>, </s>, [UNK], [PAD], [START] and [STOP] shouldn't be in "
                f"the vocab file, but {w} is")
        if w in self._word_to_id:
            raise ValueError(f"Duplicated word in vocabulary file: {w}")
        self._word_to_id[w] = self._count
        self._id_to_word[self._count] = w
        self._count += 1

    def word2id(self, word: str) -> int:
        return self._word_to_id.get(word, self._word_to_id[UNKNOWN_TOKEN])

    def id2word(self, word_id: int) -> str:
        if word_id not in self._id_to_word:
            raise ValueError(f"Id not found in vocab: {word_id}")
        return self._id_to_word[word_id]

    def size(self) -> int:
        return self._count

    def __contains__(self, word: str) -> bool:
        return word in self._word_to_id

    def words(self) -> List[str]:
        return [self._id_to_word[i] for i in range(self._count)]

    def write_metadata(self, fpath: str) -> None:
        """Embedding-projector metadata: one word per line (data.py:93-105)."""
        log.info("Writing word embedding metadata file to %s...", fpath)
        with open(fpath, "w", encoding="utf-8") as f:
            for i in range(self.size()):
                f.write(self._id_to_word[i] + "\n")
