"""Threaded, bucketing batcher.

Behavior parity with the reference Batcher
(/root/reference/src/main/python/pointer-generator/batcher.py:222-379):
producer-consumer queues (16 example threads + 4 batch threads when
streaming, 1+1 in single_pass), length-bucketing over a
100-batch cache with batch-order shuffling, decode mode repeating one
example batch_size times, a watcher thread restarting dead workers, and
empty-article skipping.

TPU-first difference: emitted Batches are static-shape (padded to
``hps.max_enc_steps``) — see batching.py.  ``decode_batch_mode='distinct'``
additionally allows batches of distinct articles in decode mode, because
the on-device beam search keeps its own beam axis and can decode a whole
batch of articles per dispatch (the reference needs the repeat because its
beam occupies the batch axis, batcher.py:344-347).
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from typing import Callable, Iterator, Optional, Tuple

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data import chunks, oov as oov_lib
from textsummarization_on_flink_tpu.data.batching import Batch, SummaryExample
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.resilience import faultinject
from textsummarization_on_flink_tpu.resilience.errors import WorkerCrashError

log = logging.getLogger(__name__)


class Batcher:
    BATCH_QUEUE_MAX = 100

    def __init__(self, data_path: str, vocab: Vocab, hps: HParams,
                 single_pass: bool, decode_batch_mode: str = "repeat",
                 watch_interval: float = 60.0,
                 example_source: Optional[Callable[[], Iterator[Tuple[str, ...]]]] = None,
                 max_worker_restarts: int = 3):
        """
        Args:
          data_path: chunk-file glob (ignored when example_source given).
          decode_batch_mode: 'repeat' mirrors the reference (one example
            repeated across the batch); 'distinct' packs distinct articles.
          example_source: optional zero-arg callable returning an iterator
            of (article, abstract) pairs or (uuid, article, abstract,
            reference) passthrough 4-tuples — the streaming-bridge hook.
          max_worker_restarts: total crash-restart budget shared by ALL
            producer threads (RESILIENCE.md).  A crashed worker restarts
            in place (re-opening its source — upstream dedup, e.g.
            ResilientSource, owns exactly-once) up to this many times;
            the budget spent, the first error surfaces from next_batch()
            as a typed WorkerCrashError.  0 restores fail-fast.
        """
        self._data_path = data_path
        self._vocab = vocab
        self._hps = hps
        self._single_pass = single_pass
        self._decode_batch_mode = decode_batch_mode
        self._example_source = example_source
        self._watch_interval = watch_interval
        self._faults = faultinject.plan_for(hps)
        # worker-crash restart budget (shared across producer threads)
        self._restarts_left = max(int(max_worker_restarts), 0)
        self._restart_lock = threading.Lock()

        self._batch_queue: "queue.Queue[Batch]" = queue.Queue(self.BATCH_QUEUE_MAX)
        self._example_queue: "queue.Queue[SummaryExample]" = queue.Queue(
            self.BATCH_QUEUE_MAX * hps.batch_size)

        if single_pass:
            self._num_example_q_threads = 1
            self._num_batch_q_threads = 1
            self._bucketing_cache_size = 1
            self._finished_reading = False
        else:
            self._num_example_q_threads = 16
            self._num_batch_q_threads = 4
            self._bucketing_cache_size = 100

        # First producer failure is recorded here and re-raised from
        # next_batch() — the consumer sees the real error instead of the
        # watcher respawning a thread that instantly re-dies (the
        # reference's worst habit, batcher.py:343-360; same contract as
        # the estimator's _BridgeFeeder.raise_if_failed).
        self._fill_error: Optional[BaseException] = None
        self._fill_error_lock = threading.Lock()

        # observability (`data/` namespace, OBSERVABILITY.md): examples
        # built, OOV volume (rate = oov_words / enc_tokens), empty-article
        # skips, batches emitted, and output-queue fill — examples/sec is
        # the counter's derivative, which the exporter snapshot carries
        reg = obs.registry_for(hps)
        self._c_restarts = reg.counter("resilience/etl_worker_restarts_total")
        self._c_examples = reg.counter("data/examples_total")
        self._c_empty = reg.counter("data/empty_articles_total")
        self._c_batches = reg.counter("data/batches_total")
        self._c_oov_words = reg.counter("data/oov_words_total")
        self._c_enc_tokens = reg.counter("data/enc_tokens_total")
        self._g_fill = reg.gauge("data/batch_queue_depth")

        self._example_q_threads = []
        for _ in range(self._num_example_q_threads):
            t = threading.Thread(target=self._run_producer,
                                 args=(self._fill_example_queue,), daemon=True)
            self._example_q_threads.append(t)
            t.start()
        self._batch_q_threads = []
        for _ in range(self._num_batch_q_threads):
            t = threading.Thread(target=self._run_producer,
                                 args=(self._fill_batch_queue,), daemon=True)
            self._batch_q_threads.append(t)
            t.start()

        if not single_pass:
            self._watch_thread = threading.Thread(target=self._watch_threads,
                                                  daemon=True)
            self._watch_thread.start()

    # -- consumer API --
    def queued_batches(self) -> int:
        """Approximate count of ready batches in the output queue (for
        consumers that want to distinguish backlog from live production,
        e.g. throughput measurement)."""
        return self._batch_queue.qsize()

    def raise_if_failed(self) -> None:
        """Re-raise the first terminal producer failure in the consumer.

        Typed as WorkerCrashError (a RuntimeError subclass, so the
        pre-existing "producer thread failed" handlers keep working): by
        the time this fires, the shared restart budget is spent and the
        underlying cause is chained."""
        err = self._fill_error
        if err is not None:
            raise WorkerCrashError(
                "batcher producer thread failed; see chained cause "
                "(worker restart budget spent)") from err

    def next_batch(self) -> Optional[Batch]:
        """Next Batch, or None when a single_pass dataset is exhausted.

        Polls rather than blocking indefinitely: end-of-stream can arrive
        AFTER a consumer is already parked in get() (the source closes with
        no further batches), so the wait must re-check _finished_reading.
        Raises if a producer thread died with an error (instead of waiting
        forever on a queue nobody is filling).
        """
        warned = False
        while True:
            try:
                batch = self._batch_queue.get(timeout=0.2)
                self._g_fill.set(self._batch_queue.qsize())
                return batch
            except queue.Empty:
                self.raise_if_failed()
                if not warned:
                    log.warning(
                        "Bucket input queue is empty when calling next_batch. "
                        "Bucket queue size: %i, Input queue size: %i",
                        self._batch_queue.qsize(), self._example_queue.qsize())
                    warned = True
                if self._single_pass and self._finished_reading and not any(
                        t.is_alive() for t in self._batch_q_threads):
                    if self._batch_queue.qsize() == 0:
                        log.info("Finished reading dataset in single_pass mode.")
                        return None

    # -- producers --
    def _consume_restart(self) -> bool:
        """Atomically take one unit of the shared restart budget."""
        with self._restart_lock:
            if self._restarts_left <= 0:
                return False
            self._restarts_left -= 1
            return True

    def _run_producer(self, fn: Callable[[], None]) -> None:
        """Thread body: run `fn`; on a crash, restart IN PLACE against
        the shared budget (RESILIENCE.md etl worker policy) — the thread
        re-runs `fn` from scratch, re-opening its source — and once the
        budget is spent record the failure for the consumer instead of
        letting it vanish in a daemon thread."""
        while True:
            try:
                fn()
                return  # clean exit (single_pass exhaustion)
            except BaseException as e:  # noqa: BLE001 — capture everything
                # a terminal failure is already recorded: this crash is
                # downstream fallout (e.g. a batch thread seeing the dead
                # example queue) — don't burn budget on it
                if self._fill_error is None and self._consume_restart():
                    self._c_restarts.inc()
                    log.warning(
                        "batcher producer crashed (%r); restarting in "
                        "place (%d restart(s) left)", e, self._restarts_left)
                    continue
                with self._fill_error_lock:
                    if self._fill_error is None:
                        self._fill_error = e
                log.error("batcher producer thread failed: %r", e)
                return

    def _text_pairs(self) -> Iterator[Tuple[str, ...]]:
        """Yields (article, abstract) or, from a streaming source,
        (uuid, article, abstract, reference) with passthrough columns
        (the FlinkExample uuid field, reference batcher.py:398-410)."""
        if self._example_source is not None:
            yield from self._example_source()
            return
        for e in chunks.example_generator(self._data_path, self._single_pass):
            article = e.get_str("article")
            abstract = e.get_str("abstract")
            if len(article) == 0:
                self._c_empty.inc()
                log.warning("Found an example with empty article text. Skipping it.")
                continue
            yield article, abstract

    def _fill_example_queue(self) -> None:
        gen = self._text_pairs()
        while True:
            if self._faults.fire("etl.worker"):
                # the natural crash class for an ETL worker: an unhandled
                # error mid-loop, driven through the same restart path a
                # real one would take
                raise RuntimeError("injected etl.worker fault")
            try:
                item = next(gen)
            except StopIteration:
                log.info("example generator exhausted data.")
                if self._single_pass:
                    self._finished_reading = True
                    break
                raise Exception(
                    "single_pass mode is off but the example generator is "
                    "out of data; error.")
            if len(item) == 4:
                uuid, article, abstract, reference = item
            else:
                article, abstract = item
                uuid, reference = "", ""
            abstract_sentences = [
                s.strip() for s in oov_lib.abstract2sents(abstract)]
            ex = SummaryExample.build(article, abstract_sentences, self._vocab,
                                      self._hps, uuid=uuid, reference=reference)
            self._c_examples.inc()
            self._c_enc_tokens.inc(ex.enc_len)
            self._c_oov_words.inc(len(ex.article_oovs))
            self._example_queue.put(ex)

    def _get_example(self, timeout: Optional[float] = None) -> Optional[SummaryExample]:
        """example_queue.get that gives up once a single_pass read finished,
        or after `timeout` seconds (None = wait indefinitely).

        The budget is MEASURED elapsed time (time.monotonic), not a count
        of nominal 0.2s poll intervals — under a slow/contended queue a
        get(timeout=0.2) can block far longer than 0.2s, and the old
        interval count let `timeout=` stretch unboundedly (ISSUE 2
        satellite: timeout accounting).
        """
        deadline = (None if timeout is None
                    else time.monotonic() + max(timeout, 0.0))
        while True:
            poll = 0.2
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                poll = min(poll, remaining)
            try:
                return self._example_queue.get(timeout=poll)
            except queue.Empty:
                if self._fill_error is not None:
                    # an example thread died; propagate so this batch
                    # thread exits too instead of waiting forever
                    raise RuntimeError("example producer thread failed")
                if self._single_pass and self._finished_reading:
                    return None

    def _put_batch(self, batch: Batch) -> None:
        self._batch_queue.put(batch)
        self._c_batches.inc()
        self._g_fill.set(self._batch_queue.qsize())

    def _fill_batch_queue(self) -> None:
        hps = self._hps
        while True:
            if hps.mode != "decode":
                inputs = []
                for _ in range(hps.batch_size * self._bucketing_cache_size):
                    ex = self._get_example()
                    if ex is None:
                        break
                    inputs.append(ex)
                if not inputs:
                    break  # single_pass exhausted
                rows = [(ex, True) for ex in inputs]
                if self._single_pass and len(rows) % hps.batch_size != 0:
                    # pad the tail batch by repeating the last example so the
                    # static batch shape holds; padding rows are tagged
                    # real=False so consumers drop exactly these (never a
                    # legitimate duplicate input)
                    pad = hps.batch_size - len(rows) % hps.batch_size
                    rows.extend([(rows[-1][0], False)] * pad)
                rows.sort(key=lambda r: r[0].enc_len)  # length bucketing
                batches = [rows[i : i + hps.batch_size]
                           for i in range(0, len(rows), hps.batch_size)]
                if not self._single_pass:
                    random.shuffle(batches)
                for b in batches:
                    self._put_batch(Batch(
                        [r[0] for r in b], hps, self._vocab,
                        real_mask=[r[1] for r in b]))
            elif self._decode_batch_mode == "repeat":
                ex = self._get_example()
                if ex is None:
                    break
                b = [ex] * hps.batch_size
                mask = [True] + [False] * (hps.batch_size - 1)
                self._put_batch(Batch(b, hps, self._vocab, real_mask=mask))
            else:  # 'distinct': fill a whole batch of different articles
                exs = []
                first = self._get_example()  # wait for the first article
                if first is None:
                    break
                exs.append(first)
                # Trickle-latency guard: top up briefly, then ship a
                # partial batch padded with repeats — a streamed article
                # must not wait for batch_size-1 neighbors to arrive.
                while len(exs) < hps.batch_size:
                    ex = self._get_example(timeout=0.2)
                    if ex is None:
                        break
                    exs.append(ex)
                n_real = len(exs)
                while len(exs) < hps.batch_size:
                    exs.append(exs[-1])
                mask = [i < n_real for i in range(hps.batch_size)]
                self._put_batch(Batch(exs, hps, self._vocab, real_mask=mask))

    def _watch_threads(self) -> None:
        while True:
            time.sleep(self._watch_interval)
            if self._fill_error is not None:
                # producers died with a real error: stop supervising and
                # let next_batch() surface it — respawning a thread that
                # instantly re-raises every interval helps nobody
                return
            for idx, t in enumerate(self._example_q_threads):
                if not t.is_alive():
                    log.error("Found example queue thread dead. Restarting.")
                    new_t = threading.Thread(
                        target=self._run_producer,
                        args=(self._fill_example_queue,), daemon=True)
                    self._example_q_threads[idx] = new_t
                    new_t.start()
            for idx, t in enumerate(self._batch_q_threads):
                if not t.is_alive():
                    log.error("Found batch queue thread dead. Restarting.")
                    new_t = threading.Thread(
                        target=self._run_producer,
                        args=(self._fill_batch_queue,), daemon=True)
                    self._batch_q_threads[idx] = new_t
                    new_t.start()
