"""Length-prefixed binary chunk files of serialized tf.Example records.

On-disk format parity with the reference (data.py:108-141 reader,
make_datafiles.py:150-209 writer): each record is an 8-byte little-endian
signed length followed by that many bytes of serialized tf.Example proto.
"""

from __future__ import annotations

import glob
import random
import struct
from typing import Iterable, Iterator, List, Optional

from textsummarization_on_flink_tpu.data.tfexample import Example


def write_chunk_file(path: str, examples: Iterable[Example]) -> int:
    """Write examples to one chunk file; returns the record count."""
    n = 0
    with open(path, "wb") as f:
        for ex in examples:
            blob = ex.serialize()
            f.write(struct.pack("<q", len(blob)))
            f.write(blob)
            n += 1
    return n


def read_chunk_file(path: str) -> Iterator[Example]:
    with open(path, "rb") as f:
        while True:
            len_bytes = f.read(8)
            if not len_bytes:
                break
            if len(len_bytes) != 8:
                raise ValueError(f"truncated length prefix in {path}")
            (str_len,) = struct.unpack("<q", len_bytes)
            blob = f.read(str_len)
            if len(blob) != str_len:
                raise ValueError(f"truncated record in {path}")
            yield Example.parse(blob)


def example_generator(data_path: str, single_pass: bool,
                      rng: Optional[random.Random] = None) -> Iterator[Example]:
    """Yield Examples from a glob of chunk files (data.py:108-141 semantics).

    single_pass=True: sorted file order, one epoch, then stop.
    single_pass=False: shuffle the file list each epoch, loop forever.
    """
    rng = rng or random.Random()
    while True:
        filelist = glob.glob(data_path)
        assert filelist, f"Error: Empty filelist at {data_path}"
        if single_pass:
            filelist = sorted(filelist)
        else:
            rng.shuffle(filelist)
        for f in filelist:
            yield from read_chunk_file(f)
        if single_pass:
            break


def write_chunked(prefix: str, examples: List[Example],
                  chunk_size: int = 1000) -> List[str]:
    """Write examples into `<prefix>_000.bin`, `<prefix>_001.bin`, ...
    (make_datafiles.py:36-64 chunking scheme)."""
    n_chunks = max((len(examples) + chunk_size - 1) // chunk_size, 1)
    width = max(3, len(str(n_chunks - 1)))  # keep lexicographic == numeric order
    paths = []
    for i in range(0, max(len(examples), 1), chunk_size):
        path = f"{prefix}_{i // chunk_size:0{width}d}.bin"
        write_chunk_file(path, examples[i : i + chunk_size])
        paths.append(path)
    return paths


def bin2txt(data_path: str, out_path: str, limit: int = 0) -> int:
    """Convert chunked bins to JSON lines for stream seeding
    (util.py:44-99 capability parity). Each line carries the example's
    article/abstract as strings."""
    import json

    def _jsonable(vals):
        vals = [v.decode("utf-8", errors="replace") if isinstance(v, bytes) else v
                for v in vals]
        return vals[0] if len(vals) == 1 else vals

    n = 0
    with open(out_path, "w", encoding="utf-8") as out:
        for ex in example_generator(data_path, single_pass=True):
            rec = {k: _jsonable(v) for k, v in ex.features.items()}
            out.write(json.dumps(rec) + "\n")
            n += 1
            if limit and n >= limit:
                break
    return n
