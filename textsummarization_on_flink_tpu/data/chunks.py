"""Length-prefixed binary chunk files of serialized tf.Example records.

On-disk format parity with the reference (data.py:108-141 reader,
make_datafiles.py:150-209 writer): each record is an 8-byte little-endian
signed length followed by that many bytes of serialized tf.Example proto.
"""

from __future__ import annotations

import glob
import random
import struct
from typing import Iterable, Iterator, List, Optional

from textsummarization_on_flink_tpu.data.tfexample import Example


def write_chunk_file(path: str, examples: Iterable[Example]) -> int:
    """Write examples to one chunk file; returns the record count."""
    n = 0
    with open(path, "wb") as f:
        for ex in examples:
            blob = ex.serialize()
            f.write(struct.pack("<q", len(blob)))
            f.write(blob)
            n += 1
    return n


# -4 (negative/oversized length prefix) reports as "truncated record" for
# exact message parity with the pure-Python reader, which hits its length
# mismatch on the same inputs
_NATIVE_ERRORS = {-2: "truncated length prefix", -3: "truncated record",
                  -4: "truncated record"}


def _native_read_blobs(path: str) -> Optional[List[bytes]]:
    """Read all record payloads via the C++ reader (native/chunkio.cpp):
    one file slurp + framing validation in native code, one contiguous
    payload buffer sliced here.  Returns None when the native library is
    unavailable or TS_NATIVE_IO=off; raises ValueError on corrupt framing
    (matching the pure-Python reader's errors)."""
    import ctypes
    import os

    if os.environ.get("TS_NATIVE_IO", "auto").lower() in ("0", "off",
                                                          "false"):
        return None
    from textsummarization_on_flink_tpu.pipeline import bridge

    lib = bridge.NativeRecordQueue.load_library()
    if lib is None or not hasattr(lib, "ts_chunk_read_file"):
        return None
    lib.ts_chunk_read_file.restype = ctypes.c_int
    lib.ts_chunk_read_file.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_longlong)),
        ctypes.POINTER(ctypes.c_longlong)]
    lib.ts_chunk_free.restype = None
    lib.ts_chunk_free.argtypes = [ctypes.POINTER(ctypes.c_char),
                                  ctypes.POINTER(ctypes.c_longlong)]
    buf = ctypes.POINTER(ctypes.c_char)()
    offs = ctypes.POINTER(ctypes.c_longlong)()
    n = ctypes.c_longlong()
    rc = lib.ts_chunk_read_file(path.encode(), ctypes.byref(buf),
                                ctypes.byref(offs), ctypes.byref(n))
    if rc == -1:
        raise OSError(f"native chunk reader cannot open {path}")
    if rc == -5:
        raise OSError(f"native chunk reader failed reading {path}")
    if rc == -6:
        raise MemoryError(f"native chunk reader allocation failed for {path}")
    if rc != 0:
        raise ValueError(
            f"{_NATIVE_ERRORS.get(rc, f'error {rc}')} in {path}")
    try:
        count = n.value
        base = ctypes.addressof(buf.contents) if count else 0
        # slice each record straight from the native buffer — no
        # whole-payload intermediate bytes object
        return [ctypes.string_at(base + offs[i], offs[i + 1] - offs[i])
                for i in range(count)]
    finally:
        lib.ts_chunk_free(buf, offs)


def read_chunk_file(path: str) -> Iterator[Example]:
    blobs = _native_read_blobs(path)
    if blobs is not None:
        for blob in blobs:
            yield Example.parse(blob)
        return
    with open(path, "rb") as f:
        while True:
            len_bytes = f.read(8)
            if not len_bytes:
                break
            if len(len_bytes) != 8:
                raise ValueError(f"truncated length prefix in {path}")
            (str_len,) = struct.unpack("<q", len_bytes)
            if str_len < 0:  # framing corruption (same report as native)
                raise ValueError(f"truncated record in {path}")
            blob = f.read(str_len)
            if len(blob) != str_len:
                raise ValueError(f"truncated record in {path}")
            yield Example.parse(blob)


def example_generator(data_path: str, single_pass: bool,
                      rng: Optional[random.Random] = None) -> Iterator[Example]:
    """Yield Examples from a glob of chunk files (data.py:108-141 semantics).

    single_pass=True: sorted file order, one epoch, then stop.
    single_pass=False: shuffle the file list each epoch, loop forever.
    """
    rng = rng or random.Random()
    while True:
        filelist = glob.glob(data_path)
        assert filelist, f"Error: Empty filelist at {data_path}"
        if single_pass:
            filelist = sorted(filelist)
        else:
            rng.shuffle(filelist)
        for f in filelist:
            yield from read_chunk_file(f)
        if single_pass:
            break


def chunk_path(prefix: str, index: int, total_chunks: int = 0) -> str:
    """The one chunk-file naming contract (make_datafiles.py:42 scheme):
    `<prefix>_NNN.bin`, width >= 3 and wide enough that lexicographic
    order equals numeric order."""
    width = max(3, len(str(max(total_chunks - 1, index))))
    return f"{prefix}_{index:0{width}d}.bin"


def write_chunked(prefix: str, examples: List[Example],
                  chunk_size: int = 1000) -> List[str]:
    """Write examples into `<prefix>_000.bin`, `<prefix>_001.bin`, ...
    (make_datafiles.py:36-64 chunking scheme)."""
    n_chunks = max((len(examples) + chunk_size - 1) // chunk_size, 1)
    paths = []
    for i in range(0, max(len(examples), 1), chunk_size):
        path = chunk_path(prefix, i // chunk_size, n_chunks)
        write_chunk_file(path, examples[i : i + chunk_size])
        paths.append(path)
    return paths


def write_chunked_iter(prefix: str, examples: Iterable[Example],
                       chunk_size: int = 1000,
                       total_chunks: int = 0) -> List[str]:
    """Streaming write_chunked: O(chunk_size) memory for arbitrarily large
    example iterables (the CNN/DM train split is ~287k stories)."""
    paths: List[str] = []
    pending: List[Example] = []

    def flush() -> None:
        path = chunk_path(prefix, len(paths), total_chunks)
        write_chunk_file(path, pending)
        paths.append(path)
        pending.clear()

    for ex in examples:
        pending.append(ex)
        if len(pending) >= chunk_size:
            flush()
    if pending or not paths:
        flush()
    return paths


def bin2txt(data_path: str, out_path: str, limit: int = 0) -> int:
    """Convert chunked bins to JSON lines for stream seeding
    (util.py:44-99 capability parity). Each line carries the example's
    article/abstract as strings."""
    import json

    def _jsonable(vals):
        vals = [v.decode("utf-8", errors="replace") if isinstance(v, bytes) else v
                for v in vals]
        return vals[0] if len(vals) == 1 else vals

    n = 0
    with open(out_path, "w", encoding="utf-8") as out:
        for ex in example_generator(data_path, single_pass=True):
            rec = {k: _jsonable(v) for k, v in ex.features.items()}
            out.write(json.dumps(rec) + "\n")
            n += 1
            if limit and n >= limit:
                break
    return n
