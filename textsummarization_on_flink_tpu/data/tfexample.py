"""Minimal pure-Python tf.train.Example protobuf codec.

The reference's on-disk and on-wire data format is the serialized
``tf.train.Example`` proto (data.py:108-141 reads it; make_datafiles.py
writes it; the Flink<->python data plane ships it as bytes).  This module
implements just enough of the proto3 wire format to encode/decode that one
message family without depending on TensorFlow or protoc-generated code:

    Example   { Features features = 1; }
    Features  { map<string, Feature> feature = 1; }
    Feature   { oneof kind { BytesList bytes_list = 1;
                             FloatList float_list = 2;
                             Int64List int64_list = 3; } }
    BytesList { repeated bytes value = 1; }
    FloatList { repeated float value = 1 [packed = true]; }
    Int64List { repeated int64 value = 1 [packed = true]; }

Wire-compatible with TensorFlow's serialization (field numbers/types from
tensorflow/core/example/{example,feature}.proto).  The decoder accepts both
packed and unpacked repeated scalars.
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Sequence, Tuple, Union

FeatureValue = Union[List[bytes], List[float], List[int]]

_WIRE_VARINT = 0
_WIRE_I64 = 1
_WIRE_LEN = 2
_WIRE_I32 = 5


def _write_varint(out: bytearray, value: int) -> None:
    if value < 0:
        value &= (1 << 64) - 1  # two's-complement for negative int64
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift >= 70:
            raise ValueError("varint too long")


def _signed64(value: int) -> int:
    return value - (1 << 64) if value >= (1 << 63) else value


def _tag(field: int, wire: int) -> int:
    return (field << 3) | wire


def _write_len_delimited(out: bytearray, field: int, payload: bytes) -> None:
    _write_varint(out, _tag(field, _WIRE_LEN))
    _write_varint(out, len(payload))
    out.extend(payload)


def _encode_bytes_list(values: Sequence[bytes]) -> bytes:
    out = bytearray()
    for v in values:
        if isinstance(v, str):
            v = v.encode("utf-8")
        _write_len_delimited(out, 1, bytes(v))
    return bytes(out)


def _encode_float_list(values: Sequence[float]) -> bytes:
    out = bytearray()
    packed = struct.pack(f"<{len(values)}f", *values)
    _write_len_delimited(out, 1, packed)
    return bytes(out)


def _encode_int64_list(values: Sequence[int]) -> bytes:
    payload = bytearray()
    for v in values:
        _write_varint(payload, int(v))
    out = bytearray()
    _write_len_delimited(out, 1, bytes(payload))
    return bytes(out)


def _encode_feature(values: FeatureValue) -> bytes:
    out = bytearray()
    if not values:
        # ambiguous empty feature: encode as empty bytes_list
        _write_len_delimited(out, 1, b"")
        return bytes(out)
    head = values[0]
    if isinstance(head, (bytes, str)):
        _write_len_delimited(out, 1, _encode_bytes_list(values))  # type: ignore[arg-type]
    elif isinstance(head, float):
        _write_len_delimited(out, 2, _encode_float_list(values))  # type: ignore[arg-type]
    elif isinstance(head, int):
        _write_len_delimited(out, 3, _encode_int64_list(values))  # type: ignore[arg-type]
    else:
        raise TypeError(f"unsupported feature value type: {type(head)}")
    return bytes(out)


def _iter_fields(buf: bytes) -> Iterator[Tuple[int, int, Union[int, bytes]]]:
    """Yield (field_number, wire_type, value) triples from a message body."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == _WIRE_VARINT:
            val, pos = _read_varint(buf, pos)
            yield field, wire, val
        elif wire == _WIRE_LEN:
            ln, pos = _read_varint(buf, pos)
            if pos + ln > len(buf):
                raise ValueError("truncated length-delimited field")
            yield field, wire, buf[pos : pos + ln]
            pos += ln
        elif wire == _WIRE_I64:
            if pos + 8 > len(buf):
                raise ValueError("truncated fixed64 field")
            yield field, wire, buf[pos : pos + 8]
            pos += 8
        elif wire == _WIRE_I32:
            if pos + 4 > len(buf):
                raise ValueError("truncated fixed32 field")
            yield field, wire, buf[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")


def _decode_scalar_list(buf: bytes, kind: str) -> FeatureValue:
    values: List = []
    for field, wire, val in _iter_fields(buf):
        if field != 1:
            continue
        if kind == "bytes":
            values.append(val)
        elif kind == "float":
            if wire == _WIRE_LEN:  # packed
                if len(val) % 4 != 0:
                    raise ValueError("truncated packed float list")
                values.extend(struct.unpack(f"<{len(val) // 4}f", val))
            elif wire == _WIRE_I32:
                values.append(struct.unpack("<f", val)[0])
        elif kind == "int64":
            if wire == _WIRE_VARINT:
                values.append(_signed64(val))
            elif wire == _WIRE_LEN:  # packed
                pos = 0
                while pos < len(val):
                    v, pos = _read_varint(val, pos)
                    values.append(_signed64(v))
    return values


class Example:
    """A tf.train.Example: a named bag of bytes/float/int64 feature lists."""

    def __init__(self, features: Dict[str, FeatureValue] | None = None):
        self.features: Dict[str, FeatureValue] = dict(features or {})

    # -- convenience accessors (mirror example.features.feature[k] usage) --
    def bytes_list(self, key: str) -> List[bytes]:
        return list(self.features.get(key, []))  # type: ignore[arg-type]

    def get_bytes(self, key: str, index: int = 0, default: bytes = b"") -> bytes:
        vals = self.features.get(key)
        if not vals or index >= len(vals):
            return default
        v = vals[index]
        return v if isinstance(v, bytes) else str(v).encode("utf-8")

    def get_str(self, key: str, index: int = 0, default: str = "") -> str:
        b = self.get_bytes(key, index, default.encode("utf-8"))
        return b.decode("utf-8", errors="replace")

    def set_bytes(self, key: str, *values: bytes) -> "Example":
        self.features[key] = [
            v.encode("utf-8") if isinstance(v, str) else bytes(v) for v in values
        ]
        return self

    def set_floats(self, key: str, *values: float) -> "Example":
        self.features[key] = [float(v) for v in values]
        return self

    def set_ints(self, key: str, *values: int) -> "Example":
        self.features[key] = [int(v) for v in values]
        return self

    # -- wire format --
    def serialize(self) -> bytes:
        feats = bytearray()
        for key in self.features:  # insertion order; fine for a map field
            entry = bytearray()
            _write_len_delimited(entry, 1, key.encode("utf-8"))
            _write_len_delimited(entry, 2, _encode_feature(self.features[key]))
            _write_len_delimited(feats, 1, bytes(entry))
        out = bytearray()
        _write_len_delimited(out, 1, bytes(feats))
        return bytes(out)

    @classmethod
    def parse(cls, data: bytes) -> "Example":
        ex = cls()
        for field, wire, val in _iter_fields(data):
            if field == 1 and wire == _WIRE_LEN:  # Features
                for f2, w2, entry in _iter_fields(val):  # map entries
                    if f2 != 1 or w2 != _WIRE_LEN:
                        continue
                    key: str = ""
                    feature_body: bytes = b""
                    for f3, w3, v3 in _iter_fields(entry):
                        if f3 == 1:
                            key = v3.decode("utf-8")  # type: ignore[union-attr]
                        elif f3 == 2:
                            feature_body = v3  # type: ignore[assignment]
                    kind_values: FeatureValue = []
                    for f4, w4, v4 in _iter_fields(feature_body):
                        if f4 == 1:
                            kind_values = _decode_scalar_list(v4, "bytes")
                        elif f4 == 2:
                            kind_values = _decode_scalar_list(v4, "float")
                        elif f4 == 3:
                            kind_values = _decode_scalar_list(v4, "int64")
                    ex.features[key] = kind_values
        return ex

    # Mutable (set_* mutate in place), hence deliberately unhashable;
    # dedup on ex.serialize() bytes instead.
    __hash__ = None  # type: ignore[assignment]

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Example) and self.features == other.features

    def __repr__(self) -> str:
        return f"Example({self.features!r})"
