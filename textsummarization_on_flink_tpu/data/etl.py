"""CNN/DailyMail ETL: story files -> tokenized, chunked tf.Example bins.

Capability parity with the reference's offline pipeline
(/root/reference/data/cnn-dailymail/make_datafiles.py):

  * PTB-style word tokenization — the reference shells out to Stanford
    CoreNLP's PTBTokenizer (:67-87); this is a dependency-free regex
    tokenizer covering the same behavior class (punctuation split,
    contraction split `don't -> do n't`, possessive split `fox's -> fox 's`,
    bracket normalization is *not* applied — the reference relies on
    downstream lowercasing only).
  * `get_art_abs` (:109-147): lowercase, fix missing periods with the
    reference's END_TOKENS list, `@highlight` blocks become the abstract
    wrapped in `<s> ... </s>`.
  * `hashhex` url -> sha1 story-file naming (:98-106).
  * `write_to_bin` (:150-209): length-prefixed serialized
    tf.Example{article, abstract} records + a 200k vocab Counter over
    article+abstract tokens.
  * `chunk_all`: 1000-example chunk files `<set>_000.bin` (:28-64).
"""

from __future__ import annotations

import collections
import glob
import hashlib
import logging
import os
import re
from typing import Iterable, List, Optional, Tuple

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.data import chunks
from textsummarization_on_flink_tpu.data.tfexample import Example
from textsummarization_on_flink_tpu.data.vocab import SENTENCE_END, SENTENCE_START

log = logging.getLogger(__name__)

dm_single_close_quote = "’"
dm_double_close_quote = "”"
# make_datafiles.py:13 verbatim list
END_TOKENS = [".", "!", "?", "...", "'", "`", '"', dm_single_close_quote,
              dm_double_close_quote, ")"]

VOCAB_SIZE = 200_000  # make_datafiles.py:32
CHUNK_SIZE = 1000  # make_datafiles.py:33

# -- tokenizer ---------------------------------------------------------------

_CONTRACTIONS = re.compile(
    r"\b(can)(not)\b|(\w+)(n't)\b|(\w+)('(?:ll|re|ve|s|m|d))\b",
    re.IGNORECASE)
_TOKEN = re.compile(
    r"n't|'(?:ll|re|ve|s|m|d)\b|"  # contraction fragments (post-split)
    r"\.\.\.|"             # ellipsis
    r"[a-zA-Z]+\.(?:[a-zA-Z]+\.)+|"  # abbreviations like u.s. / u.k.
    r"\d+(?:[.,]\d+)*|"    # numbers incl 1,000.5
    r"\w+(?:-\w+)*|"       # words and hyphenated compounds
    r"[^\w\s]",            # any single punctuation mark
    re.IGNORECASE)


def word_tokenize(text: str) -> List[str]:
    """PTB-style tokenization (CoreNLP PTBTokenizer stand-in)."""
    text = _CONTRACTIONS.sub(
        lambda m: " ".join(g for g in m.groups() if g), text)
    return _TOKEN.findall(text)


def tokenize_text(text: str) -> str:
    return " ".join(word_tokenize(text))


# -- story parsing (make_datafiles.py:109-147) -------------------------------

def fix_missing_period(line: str) -> str:
    """:109-116 — headlines/datelines often lack a closing period."""
    if not line:
        return line
    if line == "@highlight":
        return line
    if any(line.endswith(t) for t in END_TOKENS):
        return line
    return line + " ."


def get_art_abs(story_text: str, tokenize: bool = True) -> Tuple[str, str]:
    """Story text -> (article, abstract) (:119-147): lowercase, fix
    periods, split at @highlight markers, wrap highlights in <s>..</s>."""
    lines = [ln.strip() for ln in story_text.split("\n")]
    if tokenize:  # keep the @highlight markers intact through tokenization
        lines = [ln if ln.startswith("@highlight") else tokenize_text(ln)
                 for ln in lines]
    lines = [ln.lower() for ln in lines]
    lines = [fix_missing_period(ln) for ln in lines]
    article_lines: List[str] = []
    highlights: List[str] = []
    next_is_highlight = False
    for line in lines:
        if not line:
            continue
        elif line.startswith("@highlight"):
            next_is_highlight = True
        elif next_is_highlight:
            highlights.append(line)
        else:
            article_lines.append(line)
    article = " ".join(article_lines)
    abstract = " ".join(f"{SENTENCE_START} {sent} {SENTENCE_END}"
                        for sent in highlights)
    return article, abstract


# -- url hashing (make_datafiles.py:89-106) ----------------------------------

def hashhex(s: str) -> str:
    h = hashlib.sha1()
    h.update(s.encode("utf-8"))
    return h.hexdigest()


def get_url_hashes(url_list: Iterable[str]) -> List[str]:
    return [hashhex(url) for url in url_list]


def read_text_file(path: str) -> List[str]:
    with open(path, "r", encoding="utf-8") as f:
        return [line.strip() for line in f]


# -- bin writing (make_datafiles.py:150-209) ---------------------------------

def story_to_example(story_text: str, tokenize: bool = True) -> Example:
    article, abstract = get_art_abs(story_text, tokenize=tokenize)
    ex = Example()
    ex.set_bytes("article", article.encode("utf-8"))
    ex.set_bytes("abstract", abstract.encode("utf-8"))
    return ex


def write_to_bin(story_paths: List[str], out_prefix: str,
                 makevocab: bool = False,
                 vocab_counter: Optional[collections.Counter] = None,
                 chunk_size: int = CHUNK_SIZE,
                 tokenize: bool = True) -> List[str]:
    """Stories -> chunked bins `<out_prefix>_000.bin...`; optionally counts
    vocab (article+abstract tokens, <s>/</s> excluded, :182-194).

    Streams one chunk at a time (O(chunk_size) memory — the full CNN/DM
    train split is ~287k stories, far too large to hold as Examples).
    """

    c_stories = obs.counter("etl/stories_total")
    c_tokens = obs.counter("etl/tokens_total")

    def examples():
        for path in story_paths:
            with open(path, "r", encoding="utf-8") as f:
                ex = story_to_example(f.read(), tokenize=tokenize)
            art = ex.get_str("article")
            c_stories.inc()
            c_tokens.inc(art.count(" ") + 1)
            if makevocab and vocab_counter is not None:
                abs_ = ex.get_str("abstract")
                tokens = art.split() + [
                    t for t in abs_.split()
                    if t not in (SENTENCE_START, SENTENCE_END)]
                vocab_counter.update(t.strip() for t in tokens if t.strip())
            yield ex

    n_chunks = max((len(story_paths) + chunk_size - 1) // chunk_size, 1)
    with obs.span("etl/write_to_bin", prefix=os.path.basename(out_prefix)):
        return chunks.write_chunked_iter(out_prefix, examples(),
                                         chunk_size=chunk_size,
                                         total_chunks=n_chunks)


def write_vocab(counter: collections.Counter, path: str,
                size: int = VOCAB_SIZE) -> None:
    """`<word> <count>` lines, most common first (:199-203)."""
    with open(path, "w", encoding="utf-8") as f:
        n = 0
        for word, count in counter.most_common(size):
            f.write(f"{word} {count}\n")
            n += 1
    obs.gauge("etl/vocab_words").set(n)
    log.info("Finished writing vocab file %s", path)


def make_datafiles(stories_dir: str, url_dir: str, out_dir: str,
                   chunk_size: int = CHUNK_SIZE,
                   vocab_size: int = VOCAB_SIZE) -> None:
    """Full pipeline: url lists name the train/val/test splits by story
    hash (make_datafiles.py:218-244 flow, single stories dir)."""
    os.makedirs(out_dir, exist_ok=True)
    vocab_counter: collections.Counter = collections.Counter()
    for set_name, url_file in (("train", "all_train.txt"),
                               ("val", "all_val.txt"),
                               ("test", "all_test.txt")):
        urls = read_text_file(os.path.join(url_dir, url_file))
        hashes = get_url_hashes(urls)
        paths = []
        for h in hashes:
            p = os.path.join(stories_dir, h + ".story")
            if not os.path.exists(p):
                raise FileNotFoundError(
                    f"story file {p} for a url in {url_file} not found")
            paths.append(p)
        write_to_bin(paths, os.path.join(out_dir, set_name),
                     makevocab=(set_name == "train"),
                     vocab_counter=vocab_counter, chunk_size=chunk_size)
        log.info("wrote %d %s examples", len(paths), set_name)
    write_vocab(vocab_counter, os.path.join(out_dir, "vocab"),
                size=vocab_size)


# -- raw-text inference source (batcher.py:382-395) --------------------------

def raw_text_example_source(data_path: str):
    """example_source for Batcher: each file under the glob is one article
    (RawTextBatcher semantics: tokenized article, raw text as 'abstract')."""

    def source():
        filelist = sorted(glob.glob(data_path))
        assert filelist, f"Error: Empty filelist at {data_path}"
        for path in filelist:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
            article = tokenize_text(text)
            # the raw text rides along as a single abstract sentence
            yield article, f"{SENTENCE_START} {text} {SENTENCE_END}"

    return source
