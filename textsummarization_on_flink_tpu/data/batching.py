"""Example -> static-shape Batch packing for TPU.

Semantics parity with the reference's Example/Batch
(/root/reference/src/main/python/pointer-generator/batcher.py:33-219), with
one deliberate TPU-first change: the reference pads the encoder side to the
*batch* max length (batcher.py:159-167, possible because dynamic_rnn takes
dynamic shapes); XLA wants static shapes, so we pad every batch to
``hps.max_enc_steps`` (or an explicit bucket length) and rely on the padding
mask.  Likewise the reference's dynamic per-batch ``max_art_oovs``
(batcher.py:181) becomes the static ``hps.max_oov_buckets`` budget: OOV ids
at or beyond ``vocab_size + max_oov_buckets`` are clamped back to UNK in
both the extended encoder input and the target, which keeps every array id
inside the static extended vocabulary.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data import oov as oov_lib
from textsummarization_on_flink_tpu.data.vocab import (
    PAD_ID,
    START_DECODING,
    STOP_DECODING,
    Vocab,
)


def get_dec_inp_targ_seqs(sequence: Sequence[int], max_len: int, start_id: int,
                          stop_id: int) -> Tuple[List[int], List[int]]:
    """Decoder input starts with START; target ends with STOP unless
    truncated (batcher.py:84-105 semantics)."""
    inp = [start_id] + list(sequence)
    target = list(sequence)
    if len(inp) > max_len:
        inp = inp[:max_len]
        target = target[:max_len]  # no end token when truncated
    else:
        target.append(stop_id)
    assert len(inp) == len(target)
    return inp, target


@dataclasses.dataclass
class SummaryExample:
    """One tokenized/truncated article-abstract pair (batcher.py:33-122)."""

    enc_input: List[int]
    enc_len: int
    dec_input: List[int]
    target: List[int]
    dec_len: int
    enc_input_extend_vocab: List[int]
    article_oovs: List[str]
    original_article: str
    original_abstract: str
    original_abstract_sents: List[str]
    uuid: str = ""
    reference: str = ""  # passthrough column for streaming inference

    @classmethod
    def build(cls, article: str, abstract_sentences: Sequence[str], vocab: Vocab,
              hps: HParams, uuid: str = "", reference: str = "") -> "SummaryExample":
        start_id = vocab.word2id(START_DECODING)
        stop_id = vocab.word2id(STOP_DECODING)

        article_words = article.split()
        if len(article_words) > hps.max_enc_steps:
            article_words = article_words[: hps.max_enc_steps]
        enc_len = len(article_words)
        enc_input = [vocab.word2id(w) for w in article_words]

        abstract = " ".join(abstract_sentences)
        abstract_words = abstract.split()
        abs_ids = [vocab.word2id(w) for w in abstract_words]
        dec_input, target = get_dec_inp_targ_seqs(
            abs_ids, hps.max_dec_steps, start_id, stop_id)

        if hps.pointer_gen:
            enc_input_extend_vocab, article_oovs = oov_lib.article2ids(
                article_words, vocab)
            abs_ids_extend_vocab = oov_lib.abstract2ids(
                abstract_words, vocab, article_oovs)
            _, target = get_dec_inp_targ_seqs(
                abs_ids_extend_vocab, hps.max_dec_steps, start_id, stop_id)
        else:
            enc_input_extend_vocab, article_oovs = list(enc_input), []

        return cls(
            enc_input=enc_input,
            enc_len=enc_len,
            dec_input=dec_input,
            target=target,
            dec_len=len(dec_input),
            enc_input_extend_vocab=enc_input_extend_vocab,
            article_oovs=article_oovs,
            original_article=article,
            original_abstract=abstract,
            original_abstract_sents=list(abstract_sentences),
            uuid=uuid,
            reference=reference,
        )


class Batch:
    """Static-shape numpy batch (batcher.py:125-219 semantics, XLA shapes).

    Arrays:
      enc_batch                (B, enc_steps) int32, UNK-mapped ids
      enc_lens                 (B,)           int32
      enc_padding_mask         (B, enc_steps) float32
      enc_batch_extend_vocab   (B, enc_steps) int32, temp OOV ids (clamped)
      dec_batch                (B, dec_steps) int32
      target_batch             (B, dec_steps) int32 (extended ids, clamped)
      dec_padding_mask         (B, dec_steps) float32
    """

    def __init__(self, example_list: Sequence[SummaryExample], hps: HParams,
                 vocab: Vocab, enc_steps: Optional[int] = None,
                 real_mask: Optional[Sequence[bool]] = None):
        """``real_mask[i]`` is False for rows that are padding repeats
        (beam repetition in decode 'repeat' mode, tail/trickle padding) —
        consumers emit one result per True row, so two legitimately
        identical input rows still produce two outputs."""
        if len(example_list) != hps.batch_size:
            raise ValueError(
                f"expected {hps.batch_size} examples, got {len(example_list)}")
        if real_mask is not None and len(real_mask) != len(example_list):
            raise ValueError(
                f"real_mask has {len(real_mask)} entries for "
                f"{len(example_list)} examples")
        self.real_mask: List[bool] = (
            list(real_mask) if real_mask is not None
            else [True] * len(example_list))
        self.pad_id = PAD_ID
        B = hps.batch_size
        T_enc = enc_steps if enc_steps is not None else hps.max_enc_steps
        T_dec = hps.max_dec_steps
        vsize = vocab.size()
        oov_limit = vsize + hps.max_oov_buckets
        unk = 0

        self.enc_batch = np.full((B, T_enc), self.pad_id, dtype=np.int32)
        self.enc_lens = np.zeros((B,), dtype=np.int32)
        self.enc_padding_mask = np.zeros((B, T_enc), dtype=np.float32)
        self.enc_batch_extend_vocab = np.full((B, T_enc), self.pad_id, dtype=np.int32)
        self.dec_batch = np.full((B, T_dec), self.pad_id, dtype=np.int32)
        self.target_batch = np.full((B, T_dec), self.pad_id, dtype=np.int32)
        self.dec_padding_mask = np.zeros((B, T_dec), dtype=np.float32)

        for i, ex in enumerate(example_list):
            L = min(ex.enc_len, T_enc)
            self.enc_batch[i, :L] = ex.enc_input[:L]
            self.enc_lens[i] = L
            self.enc_padding_mask[i, :L] = 1.0
            ext = np.asarray(ex.enc_input_extend_vocab[:L], dtype=np.int32)
            ext = np.where(ext >= oov_limit, unk, ext)  # static OOV budget
            self.enc_batch_extend_vocab[i, :L] = ext
            D = min(ex.dec_len, T_dec)
            self.dec_batch[i, :D] = ex.dec_input[:D]
            tgt = np.asarray(ex.target[:D], dtype=np.int32)
            tgt = np.where(tgt >= oov_limit, unk, tgt)
            self.target_batch[i, :D] = tgt
            self.dec_padding_mask[i, :D] = 1.0

        # max over batch of (clamped) in-article OOV counts — informational,
        # the model always uses the static budget
        self.max_art_oovs = max(
            (min(len(ex.article_oovs), hps.max_oov_buckets) for ex in example_list),
            default=0)
        self.art_oovs = [ex.article_oovs for ex in example_list]
        self.original_articles = [ex.original_article for ex in example_list]
        self.original_abstracts = [ex.original_abstract for ex in example_list]
        self.original_abstracts_sents = [
            ex.original_abstract_sents for ex in example_list]
        self.uuids = [ex.uuid for ex in example_list]
        self.references = [ex.reference for ex in example_list]

    def as_arrays(self) -> Dict[str, np.ndarray]:
        """The device-feedable dict (everything static-shape)."""
        return {
            "enc_batch": self.enc_batch,
            "enc_lens": self.enc_lens,
            "enc_padding_mask": self.enc_padding_mask,
            "enc_batch_extend_vocab": self.enc_batch_extend_vocab,
            "dec_batch": self.dec_batch,
            "target_batch": self.target_batch,
            "dec_padding_mask": self.dec_padding_mask,
        }
