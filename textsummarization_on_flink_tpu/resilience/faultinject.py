"""Deterministic, seeded fault injection (ISSUE 2 tentpole).

Chaos tests (and staging soaks) drive the recovery paths through *named
injection points* compiled into the production code:

  ==================  =====================================================
  point               where it fires
  ==================  =====================================================
  ``io.connect``      pipeline/io.py — source/sink socket connect
  ``io.read``         pipeline/io.py — per-record stream read
  ``io.write``        pipeline/io.py — per-record sink write
  ``ckpt.load``       checkpoint/checkpointer.py — checksum-verified load
  ``train.step_nan``  train/trainer.py — per-dispatch divergence watchdog
  ``etl.worker``      data/batcher.py — example-producer worker loop
  ``serve.dispatch``  serve/server.py — per-(sub-)batch / per-tick dispatch
  ``serve.replica_kill``  serve/fleet.py — kills one fleet replica
                      mid-decode (residents/queued requeue on survivors)
  ``serve.cache_fault``  serve/frontdoor.py — summary-cache layer
                      failure (lookups degrade to miss-and-decode,
                      inserts drop; never a wrong summary or a hang)
  ``serve.proc_kill``  serve/procfleet.py — SIGKILLs one live replica
                      CHILD PROCESS mid-decode (the supervisor detects
                      the death, orphans requeue on survivors, the
                      child restarts under backoff)
  ``serve.arena_full``  serve/batcher.py — page-arena allocation failure
                      at slot refill (the admission REQUEUES under typed
                      ArenaExhaustedError backpressure until a harvest
                      frees pages; never a wrong decode, never a drop)
  ==================  =====================================================

Arming — either source, same ``point:prob:seed[:max]`` syntax, comma-
separated::

    TS_FAULTS="io.read:0.2:42,train.step_nan:1.0:7:3"   # environment
    HParams(faults="ckpt.load:1.0:0:1")                 # per-job

``prob`` is the per-call fire probability, ``seed`` pins the point's own
``random.Random`` stream (every run fires on the same call indices — the
chaos suite asserts exact recovery sequences), and the optional ``max``
caps total fires (so ``prob=1.0`` can model "this dependency fails
exactly N times then heals").

Call sites do ``plan.fire("io.read")`` and raise their own natural error
type when it returns True — the registry never fabricates exceptions, so
an injected fault exercises the SAME except-clauses a real one would.

Disabled mode: with nothing armed, call sites hold the shared
``NULL_PLAN`` whose ``fire()`` is a constant ``return False`` — one
attribute call on the hot path, mirroring obs/'s null-registry gating
(and the same <2% bench bar).  Import-light: no jax/numpy.
"""

from __future__ import annotations

import logging
import os
import random
import threading
from typing import Any, Dict, List, NamedTuple, Optional

from textsummarization_on_flink_tpu import obs

log = logging.getLogger(__name__)

ENV_VAR = "TS_FAULTS"

# the compiled-in injection points; parse rejects unknown names so a
# typo'd TS_FAULTS fails loudly instead of silently injecting nothing
KNOWN_POINTS = (
    "io.connect", "io.read", "io.write",
    "ckpt.load", "train.step_nan", "etl.worker",
    "serve.dispatch", "serve.replica_kill", "serve.cache_fault",
    "serve.proc_kill", "serve.arena_full",
)


class FaultSpec(NamedTuple):
    point: str
    prob: float
    seed: int
    max_fires: int  # 0 = unbounded


def parse_spec(token: str) -> FaultSpec:
    """One ``point:prob:seed[:max]`` token -> FaultSpec (validated)."""
    parts = token.strip().split(":")
    if len(parts) not in (3, 4):
        raise ValueError(
            f"bad fault spec {token!r}: want point:prob:seed[:max]")
    point = parts[0].strip()
    if point not in KNOWN_POINTS:
        raise ValueError(f"unknown fault point {point!r}; known: "
                         f"{', '.join(KNOWN_POINTS)}")
    prob = float(parts[1])
    if not 0.0 <= prob <= 1.0:
        raise ValueError(f"fault prob must be in [0, 1], got {prob}")
    seed = int(parts[2])
    max_fires = int(parts[3]) if len(parts) == 4 else 0
    if max_fires < 0:
        raise ValueError(f"fault max_fires must be >= 0, got {max_fires}")
    return FaultSpec(point, prob, seed, max_fires)


def parse(spec: str) -> List[FaultSpec]:
    """A full ``TS_FAULTS`` string -> list of FaultSpecs ('' -> [])."""
    spec = (spec or "").strip()
    if not spec:
        return []
    return [parse_spec(tok) for tok in spec.split(",") if tok.strip()]


class _Point:
    """One armed injection point: its own seeded RNG + fire budget."""

    __slots__ = ("spec", "rng", "calls", "fires", "lock", "counter")

    def __init__(self, spec: FaultSpec, registry: obs.Registry):
        self.spec = spec
        self.rng = random.Random(spec.seed)
        self.calls = 0
        self.fires = 0
        self.lock = threading.Lock()
        self.counter = registry.counter(f"resilience/fault/{spec.point}")


class FaultPlan:
    """The armed set of injection points.

    ``fire(point)`` returns True when the point's seeded RNG decides this
    call fails (and the fire budget allows).  Unarmed points return False
    at the cost of one dict miss.  Thread-safe per point (batcher worker
    threads share a plan).
    """

    enabled = True

    def __init__(self, specs: List[FaultSpec],
                 registry: Optional[obs.Registry] = None):
        reg = registry if registry is not None else obs.registry()
        self._points: Dict[str, _Point] = {
            s.point: _Point(s, reg) for s in specs}
        self._c_total = reg.counter("resilience/faults_fired_total")
        if self._points:
            log.info("fault injection armed: %s",
                     ", ".join(f"{s.point}(p={s.prob},seed={s.seed}"
                               + (f",max={s.max_fires}" if s.max_fires else "")
                               + ")"
                               for s in (p.spec for p in
                                         self._points.values())))

    def fire(self, point: str) -> bool:
        p = self._points.get(point)
        if p is None:
            return False
        with p.lock:
            p.calls += 1
            if p.spec.max_fires and p.fires >= p.spec.max_fires:
                return False
            if p.rng.random() >= p.spec.prob:
                return False
            p.fires += 1
        p.counter.inc()
        self._c_total.inc()
        log.warning("fault injected at %s (fire %d, call %d)",
                    point, p.fires, p.calls)
        return True

    def armed(self, point: str) -> bool:
        return point in self._points

    def stats(self) -> Dict[str, Dict[str, int]]:
        """{point: {calls, fires}} — chaos-test introspection."""
        return {name: {"calls": p.calls, "fires": p.fires}
                for name, p in self._points.items()}


class _NullPlan:
    """Disabled-mode singleton: fire() is a constant False."""

    enabled = False

    def fire(self, point: str) -> bool:
        return False

    def armed(self, point: str) -> bool:
        return False

    def stats(self) -> Dict[str, Dict[str, int]]:
        return {}


NULL_PLAN = _NullPlan()

_default: Optional[Any] = None
_default_lock = threading.Lock()


def plan() -> Any:
    """The process-wide plan, resolved from TS_FAULTS on first use
    (NULL_PLAN when unset/empty — the fast path)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                specs = parse(os.environ.get(ENV_VAR, ""))
                _default = FaultPlan(specs) if specs else NULL_PLAN
    return _default


def set_default_plan(p: Optional[Any]) -> None:
    """Swap the process default (None re-resolves TS_FAULTS on next use)."""
    global _default
    with _default_lock:
        _default = p


class use_plan:
    """Context manager: route ``plan()`` through `p` (chaos tests)."""

    def __init__(self, p: Any):
        self._p = p
        self._prev: Optional[Any] = None

    def __enter__(self) -> Any:
        global _default
        with _default_lock:
            self._prev = _default
            _default = self._p
        return self._p

    def __exit__(self, exc_type, exc, tb) -> None:
        global _default
        with _default_lock:
            _default = self._prev


def plan_for(hps: Any) -> Any:
    """The plan a component should consult: a per-job plan when the
    HParams carry a non-empty ``faults`` spec, else the process default
    (TS_FAULTS).  Mirrors obs.registry_for gating."""
    spec = getattr(hps, "faults", "") if hps is not None else ""
    if spec:
        return FaultPlan(parse(spec), registry=obs.registry_for(hps))
    return plan()
