"""Retry, deadline, and circuit-breaker primitives (ISSUE 2 tentpole).

The reference system's only failure policy is the decoder's infinite
10-second checkpoint retry (util.py:29-41); everything else either hangs
(pipeline/io.py's ``settimeout(None)`` stream read) or dies (the
trainer's hard NaN abort).  These three primitives replace that with
bounded, observable behavior:

  * ``RetryPolicy`` — exponential backoff with decorrelated jitter
    (the AWS-architecture-blog formula: ``sleep = min(cap,
    uniform(base, prev * 3))``), seeded for deterministic tests,
    deadline-aware, obs-instrumented.
  * ``Deadline`` — a monotonic-clock budget that request paths thread
    through blocking calls (``remaining()`` feeds socket timeouts,
    ``check()`` raises the typed error).
  * ``CircuitBreaker`` — classic closed/open/half-open: `threshold`
    consecutive failures open the circuit, calls are shed for
    ``reset_secs``, then one half-open probe decides re-close vs re-open.

All three report through ``resilience/*`` obs metrics and cost nothing
when obs is disabled (the null-registry fast path).  Import-light by
design: no jax/numpy, safe for the data/pipeline layers.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Iterator, Optional, Tuple, Type

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.obs import flightrec
from textsummarization_on_flink_tpu.obs import locksan
from textsummarization_on_flink_tpu.resilience.errors import (
    CircuitOpenError,
    DeadlineExceededError,
    RetriesExhaustedError,
)


class Deadline:
    """A wall-clock budget carried through an operation.

    Built on ``time.monotonic`` (never wall-clock, which can jump).
    ``Deadline.never()`` is the no-op deadline for unbounded callers.
    """

    __slots__ = ("_expires",)

    def __init__(self, expires_at: Optional[float]):
        self._expires = expires_at  # monotonic timestamp; None = never

    @classmethod
    def after(cls, secs: Optional[float]) -> "Deadline":
        """Deadline `secs` from now; None or <= 0 means no deadline."""
        if secs is None or secs <= 0:
            return cls(None)
        return cls(time.monotonic() + secs)

    @classmethod
    def never(cls) -> "Deadline":
        return cls(None)

    @property
    def bounded(self) -> bool:
        return self._expires is not None

    def remaining(self) -> float:
        """Seconds left (clamped at 0); +inf when unbounded."""
        if self._expires is None:
            return float("inf")
        return max(0.0, self._expires - time.monotonic())

    def expired(self) -> bool:
        return self._expires is not None and time.monotonic() >= self._expires

    def check(self, what: str = "operation") -> None:
        """Raise DeadlineExceededError if expired."""
        if self.expired():
            raise DeadlineExceededError(f"deadline exceeded during {what}")

    def timeout_for(self, default: Optional[float] = None) -> Optional[float]:
        """A value suitable for a blocking call's ``timeout=``: the lesser
        of the remaining budget and `default` (None = just the budget)."""
        if self._expires is None:
            return default
        rem = self.remaining()
        return rem if default is None else min(rem, default)


class RetryPolicy:
    """Bounded retries with exponential backoff + decorrelated jitter.

    Usage — generator style (the caller owns the try/except):

        policy = RetryPolicy(max_attempts=5, base_delay=0.05)
        for attempt in policy.attempts():   # sleeps BETWEEN attempts
            try:
                return connect()
            except OSError as e:
                policy.note_failure(e)      # raises when exhausted

    or callable style::

        policy.call(connect, retry_on=(OSError,))

    ``seed`` pins the jitter RNG (chaos tests assert exact backoff
    sequences); ``sleep`` is injectable for zero-wall-clock tests.
    ``name`` scopes the obs counters: ``resilience/<name>/retries_total``
    and ``.../retry_exhausted_total`` (plus the subsystem-wide
    ``resilience/retries_total``).
    """

    def __init__(self, max_attempts: int = 5, base_delay: float = 0.05,
                 max_delay: float = 30.0, seed: Optional[int] = None,
                 name: str = "", sleep: Callable[[float], None] = time.sleep,
                 deadline: Optional[Deadline] = None,
                 registry: Optional[obs.Registry] = None):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        if base_delay <= 0 or max_delay < base_delay:
            raise ValueError("need 0 < base_delay <= max_delay")
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.name = name
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._deadline = deadline if deadline is not None else Deadline.never()
        self._last_error: Optional[BaseException] = None
        self._failures = 0
        self._prev_delay = base_delay
        reg = registry if registry is not None else obs.registry()
        scope = f"resilience/{name}" if name else "resilience"
        self._c_retries = reg.counter(f"{scope}/retries_total")
        self._c_exhausted = reg.counter(f"{scope}/retry_exhausted_total")
        self._c_all = reg.counter("resilience/retries_total")

    def next_delay(self) -> float:
        """Decorrelated jitter: ``min(cap, uniform(base, prev * 3))``."""
        d = min(self.max_delay,
                self._rng.uniform(self.base_delay, self._prev_delay * 3))
        self._prev_delay = d
        return d

    def note_failure(self, err: BaseException) -> None:
        """Record a failed attempt.  Raises RetriesExhaustedError (cause
        chained) when the budget is spent — callers in generator style
        call this from their except block."""
        self._failures += 1  # tslint: disable=TS009 — a RetryPolicy instance is confined to ONE attempt loop; the reader-thread root is a different instance
        self._last_error = err  # tslint: disable=TS009 — same confinement: per-call-site instance, never shared across the roots the analyzer unions
        if self._failures >= self.max_attempts:
            self._c_exhausted.inc()
            raise RetriesExhaustedError(
                f"{self.name or 'operation'} failed after "
                f"{self._failures} attempts") from err

    def attempts(self) -> Iterator[int]:
        """Yield attempt indices 0..max_attempts-1, sleeping the backoff
        delay before every retry (never before the first attempt).
        Honors the deadline: expiry between attempts raises
        DeadlineExceededError with the last failure chained."""
        for attempt in range(self.max_attempts):
            if attempt > 0:
                # timeout_for never returns None here (default given) and
                # an expired deadline yields 0.0 — sleep nothing, then the
                # post-sleep check below raises immediately
                delay = min(self.next_delay(),
                            self._deadline.timeout_for(self.max_delay))
                self._c_retries.inc()
                self._c_all.inc()
                self._sleep(delay)
                if self._deadline.expired():
                    raise DeadlineExceededError(
                        f"deadline exceeded retrying "
                        f"{self.name or 'operation'}") from self._last_error
            yield attempt

    def call(self, fn: Callable[..., Any], *args: Any,
             retry_on: Tuple[Type[BaseException], ...] = (Exception,),
             **kwargs: Any) -> Any:
        """Run `fn`, retrying on `retry_on` with backoff; re-raises
        RetriesExhaustedError (last cause chained) when spent."""
        for _attempt in self.attempts():
            try:
                return fn(*args, **kwargs)
            except retry_on as e:  # noqa: PERF203 — retry loop by design
                self.note_failure(e)
        raise AssertionError("unreachable")  # pragma: no cover


class CircuitBreaker:
    """Closed / open / half-open circuit breaker.

    * CLOSED: calls flow; `threshold` CONSECUTIVE failures trip it open.
    * OPEN: ``allow()`` is False (callers shed) until ``reset_secs``
      elapse, then the breaker moves to HALF_OPEN.
    * HALF_OPEN: EXACTLY ONE in-flight probe is allowed (the
      ``_probe_out`` token, taken and released under the breaker lock);
      concurrent half-open callers lose the race and are SHED — they
      see the breaker as effectively open, they do not all probe at
      once.  Probe success re-closes, failure re-opens (and restarts
      the reset clock).  The probe token carries a LEASE: a probe whose
      caller vanished without ever recording an outcome (crashed
      thread, dropped future) expires after another ``reset_secs``, so
      a lost probe degrades into one more probe-sized delay instead of
      wedging the breaker half-open (shedding everything) forever.

    Thread-safe; ``clock`` is injectable for deterministic tests.  The
    obs gauge ``resilience/<name>/breaker_state`` exports 0=closed,
    1=half-open, 2=open; trips/sheds are counted.
    """

    CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
    _STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def __init__(self, threshold: int = 5, reset_secs: float = 30.0,
                 name: str = "breaker",
                 clock: Callable[[], float] = time.monotonic,
                 registry: Optional[obs.Registry] = None):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = threshold
        self.reset_secs = reset_secs
        self.name = name
        self._clock = clock
        self._lock = locksan.make_lock("CircuitBreaker._lock")
        self._state = self.CLOSED
        self._failures = 0  # consecutive, in CLOSED
        self._opened_at = 0.0
        self._probe_out = False  # a HALF_OPEN probe is in flight
        self._probe_at = 0.0  # when the in-flight probe was granted
        reg = registry if registry is not None else obs.registry()
        self._registry = reg
        self._g_state = reg.gauge(f"resilience/{name}/breaker_state")
        self._c_trips = reg.counter(f"resilience/{name}/breaker_trips_total")
        self._c_shed = reg.counter(f"resilience/{name}/breaker_shed_total")
        self._g_state.set(0)

    def _set_state(self, state: str) -> None:
        self._state = state
        self._g_state.set(self._STATE_CODE[state])

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        if (self._state == self.OPEN
                and self._clock() - self._opened_at >= self.reset_secs):
            self._set_state(self.HALF_OPEN)
            self._probe_out = False

    def allow(self) -> bool:
        """True if a call may proceed now.  In HALF_OPEN exactly one
        in-flight probe is allowed; concurrent callers are shed (they
        must see the breaker as open, not all probe at once).  A probe
        whose caller never reported an outcome expires after
        ``reset_secs`` and its slot re-grants."""
        with self._lock:
            self._maybe_half_open()
            if self._state == self.CLOSED:
                return True
            if self._state == self.HALF_OPEN:
                if (self._probe_out
                        and self._clock() - self._probe_at >= self.reset_secs):
                    # the lease expired: the probe's caller died without
                    # recording success/failure — presume it lost and
                    # hand the (single) probe slot to this caller
                    self._probe_out = False
                if not self._probe_out:
                    self._probe_out = True
                    self._probe_at = self._clock()
                    return True
            self._c_shed.inc()
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_out = False
            if self._state != self.CLOSED:
                self._set_state(self.CLOSED)

    def record_failure(self) -> None:
        tripped = False
        with self._lock:
            if self._state == self.HALF_OPEN:
                # the probe failed: back to OPEN, clock restarts
                self._set_state(self.OPEN)
                self._opened_at = self._clock()
                self._probe_out = False
                self._c_trips.inc()
                tripped = True
            else:
                self._failures += 1
                if (self._state == self.CLOSED
                        and self._failures >= self.threshold):
                    self._set_state(self.OPEN)
                    self._opened_at = self._clock()
                    self._c_trips.inc()
                    tripped = True
        if tripped:
            # flight-recorder trigger OUTSIDE the breaker lock (the dump
            # is file IO): an opening breaker is exactly the moment the
            # preceding steps/ticks stop being reconstructable later
            flightrec.trigger(self._registry, f"breaker_{self.name}_open")

    def __enter__(self) -> "CircuitBreaker":
        if not self.allow():
            raise CircuitOpenError(f"circuit {self.name!r} is open")
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.record_success()
        else:
            self.record_failure()
