"""Resilience subsystem: retry/backoff, deadlines, circuit breaking,
fault injection, and the typed failure vocabulary (ISSUE 2 tentpole).

The reference system is a long-running streaming service (a Flink job
training from one Kafka topic and serving another); transient faults —
dead peers, corrupted checkpoints, NaN steps, crashed workers — must
degrade gracefully instead of hanging or killing the job.  PR 1's obs/
layer made failures *visible*; this package makes the system *survive*
them.  See RESILIENCE.md for the policy inventory, injection-point
names, gating, and degradation semantics.

Wiring (each layer owns its policy, this package owns the primitives):

  * train/trainer.py — NaN/Inf divergence recovery: skip, then roll back
    to the last good checkpoint with an LR cut, then ``NanLossError``.
  * checkpoint/checkpointer.py — checksum manifests on save, verify on
    load, fall back to the next-older checkpoint on corruption.
  * pipeline/io.py — stream idle timeouts (``StreamIdleError``),
    reconnect-with-backoff sources, circuit-broken sinks.
  * data/batcher.py — bounded worker-crash restart budget before a typed
    ``WorkerCrashError``.
  * decode/decoder.py — per-request ``Deadline``; beam search degrades
    to greedy near the deadline, tagging the response degraded.

Everything reports through ``resilience/*`` obs metrics; with
``TS_FAULTS`` unset and default HParams every hook is a null-singleton
no-op (same <2% overhead bar as obs/).  Import-light: no jax/numpy.
"""

from __future__ import annotations

from textsummarization_on_flink_tpu.resilience.errors import (
    CheckpointCorruptError,
    CircuitOpenError,
    DeadlineExceededError,
    ResilienceError,
    RetriesExhaustedError,
    StreamIdleError,
    WorkerCrashError,
)
from textsummarization_on_flink_tpu.resilience.faultinject import (
    FaultPlan,
    FaultSpec,
    NULL_PLAN,
    parse as parse_faults,
    plan,
    plan_for,
    set_default_plan,
    use_plan,
)
from textsummarization_on_flink_tpu.resilience.policy import (
    CircuitBreaker,
    Deadline,
    RetryPolicy,
)

__all__ = [
    "CheckpointCorruptError", "CircuitBreaker", "CircuitOpenError",
    "Deadline", "DeadlineExceededError", "FaultPlan", "FaultSpec",
    "NULL_PLAN", "ResilienceError", "RetriesExhaustedError", "RetryPolicy",
    "StreamIdleError", "WorkerCrashError", "parse_faults", "plan",
    "plan_for", "set_default_plan", "use_plan",
]
