"""Typed failure vocabulary for the resilience subsystem (ISSUE 2).

Every recovery path in the stack surfaces one of these instead of a bare
RuntimeError/OSError, so callers can route on failure *class*:

  * ``StreamIdleError`` — a long-lived stream source saw no data for the
    idle window (the pipeline/io.py dead-peer hang, fixed by never
    leaving a socket with ``settimeout(None)``).  Subclasses
    ``TimeoutError`` so generic timeout handlers keep working.
  * ``DeadlineExceededError`` — a ``Deadline`` expired mid-operation.
    Also a ``TimeoutError`` subclass.
  * ``CircuitOpenError`` — a ``CircuitBreaker`` refused the call (the
    protected dependency is shedding load).
  * ``RetriesExhaustedError`` — a ``RetryPolicy`` ran out of attempts;
    the last cause is chained.
  * ``CheckpointCorruptError`` — a checkpoint failed its checksum
    manifest verification (checkpoint/checkpointer.py falls back to the
    next-older checkpoint before surfacing this).
  * ``WorkerCrashError`` — a worker-thread pool (batcher producers)
    exhausted its restart budget; the first underlying error is chained.
    Subclasses ``RuntimeError`` so the pre-existing "producer thread
    failed" handlers keep working.
  * ``ArenaExhaustedError`` — the paged-resident-state page arena
    (decode/arena.PageArena, ISSUE 20) has fewer free pages than an
    admission needs.  BACKPRESSURE, not failure: the ContinuousBatcher
    requeues the admission until a harvest frees pages.  Defined here
    (not in decode/) so the jax-free serve scheduler can catch it
    without importing the jax-heavy decode package.

``NanLossError`` (divergence recovery gave up) lives in
train/trainer.py next to its ``NonFiniteLossError`` base — the trainer
owns the watchdog contract and this package must stay import-light.
"""

from __future__ import annotations


class ResilienceError(RuntimeError):
    """Base class for resilience-subsystem failures."""


class StreamIdleError(ResilienceError, TimeoutError):
    """A stream source idled past its idle window (dead peer suspected)."""


class DeadlineExceededError(ResilienceError, TimeoutError):
    """A Deadline expired before the operation completed."""


class CircuitOpenError(ResilienceError):
    """The circuit breaker is open; the call was shed, not attempted."""


class RetriesExhaustedError(ResilienceError):
    """A RetryPolicy ran out of attempts (last cause chained)."""


class CheckpointCorruptError(ResilienceError):
    """A checkpoint file failed checksum-manifest verification."""


class WorkerCrashError(ResilienceError):
    """A worker-thread pool exhausted its crash-restart budget."""


class ArenaExhaustedError(ResilienceError):
    """The page arena has fewer free pages than an admission needs
    (typed allocation-failure backpressure; carries the shortfall)."""

    def __init__(self, message: str, needed: int = 0, free: int = 0):
        super().__init__(message)
        self.needed = int(needed)
        self.free = int(free)
