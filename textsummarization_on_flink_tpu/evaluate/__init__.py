from textsummarization_on_flink_tpu.evaluate import rouge  # noqa: F401
