"""Native ROUGE-1/2/L scoring with bootstrap confidence intervals.

Replaces the reference's Perl ROUGE-1.5.5 via pyrouge
(/root/reference/src/main/python/pointer-generator/decode.py:268-301) with
a dependency-free implementation of the same measures:

  * ROUGE-N (N=1,2): clipped n-gram recall/precision/F1 over the whole
    summary (Lin 2004 eq. 1), computed per document.
  * ROUGE-L: summary-level LCS with union-LCS across sentence pairs
    (Lin 2004 §3.2) — for each reference sentence, the union of LCS
    matches against all candidate sentences counts as hits.
  * 95% confidence intervals by bootstrap resampling over documents
    (ROUGE-1.5.5's default -n 1000 resampling), reported like pyrouge's
    `rouge_log` output (decode.py:280-293).

Tokenization mirrors ROUGE-1.5.5's default: lowercase, alphanumeric token
split (no stemming, no stopword removal — the reference calls pyrouge
without -m/-s).
"""

from __future__ import annotations

import dataclasses
import glob
import logging
import os
import re
from collections import Counter
from typing import Dict, List, Sequence, Tuple

import numpy as np

log = logging.getLogger(__name__)

_TOKEN_RE = re.compile(r"[a-z0-9]+")


def tokenize(text: str) -> List[str]:
    return _TOKEN_RE.findall(text.lower())


def _ngrams(tokens: Sequence[str], n: int) -> Counter:
    return Counter(tuple(tokens[i:i + n]) for i in range(len(tokens) - n + 1))


@dataclasses.dataclass(frozen=True)
class Score:
    recall: float
    precision: float
    f: float


def _prf(hits: int, peer_total: int, model_total: int) -> Score:
    p = hits / peer_total if peer_total else 0.0
    r = hits / model_total if model_total else 0.0
    f = 2 * p * r / (p + r) if p + r else 0.0
    return Score(recall=r, precision=p, f=f)


def rouge_n(peer_sents: Sequence[str], model_sents: Sequence[str],
            n: int) -> Score:
    """Clipped n-gram overlap for one document.

    peer = system/decoded summary; model = gold reference summary
    (ROUGE-1.5.5 vocabulary).  Sentences are concatenated: ROUGE-N is a
    bag-of-ngrams measure over the full summary.
    """
    peer = _ngrams([t for s in peer_sents for t in tokenize(s)], n)
    model = _ngrams([t for s in model_sents for t in tokenize(s)], n)
    hits = sum(min(c, peer[g]) for g, c in model.items())
    return _prf(hits, sum(peer.values()), sum(model.values()))


def _lcs_table(a: Sequence[str], b: Sequence[str]) -> np.ndarray:
    la, lb = len(a), len(b)
    dp = np.zeros((la + 1, lb + 1), dtype=np.int32)
    for i in range(1, la + 1):
        ai = a[i - 1]
        row = dp[i]
        prev = dp[i - 1]
        for j in range(1, lb + 1):
            if ai == b[j - 1]:
                row[j] = prev[j - 1] + 1
            else:
                row[j] = row[j - 1] if row[j - 1] >= prev[j] else prev[j]
    return dp


def _lcs_match_positions(a: Sequence[str], b: Sequence[str]) -> set:
    """Positions in `a` participating in one LCS of a vs b."""
    dp = _lcs_table(a, b)
    out = set()
    i, j = len(a), len(b)
    while i > 0 and j > 0:
        if a[i - 1] == b[j - 1] and dp[i][j] == dp[i - 1][j - 1] + 1:
            out.add(i - 1)
            i -= 1
            j -= 1
        elif dp[i - 1][j] >= dp[i][j - 1]:
            i -= 1
        else:
            j -= 1
    return out


def rouge_l(peer_sents: Sequence[str], model_sents: Sequence[str]) -> Score:
    """Summary-level ROUGE-L with union LCS (Lin 2004 §3.2).

    For each model (reference) sentence r_i, the union over all peer
    sentences of LCS(r_i, c_j) positions counts as hits; totals are the
    summary word counts.
    """
    peer_tok = [tokenize(s) for s in peer_sents]
    model_tok = [tokenize(s) for s in model_sents]
    peer_total = sum(len(t) for t in peer_tok)
    model_total = sum(len(t) for t in model_tok)
    hits = 0
    for r in model_tok:
        union: set = set()
        for c in peer_tok:
            if r and c:
                union |= _lcs_match_positions(r, c)
        hits += len(union)
    return _prf(hits, peer_total, model_total)


def score_document(peer_sents: Sequence[str], model_sents: Sequence[str],
                   ) -> Dict[str, Score]:
    return {
        "rouge_1": rouge_n(peer_sents, model_sents, 1),
        "rouge_2": rouge_n(peer_sents, model_sents, 2),
        "rouge_l": rouge_l(peer_sents, model_sents),
    }


def _bootstrap_ci(values: np.ndarray, n_samples: int = 1000,
                  seed: int = 0) -> Tuple[float, float]:
    """95% CI of the mean by bootstrap resampling over documents
    (ROUGE-1.5.5 default resampling protocol)."""
    if len(values) == 0:
        return (0.0, 0.0)
    rng = np.random.RandomState(seed)
    idx = rng.randint(0, len(values), size=(n_samples, len(values)))
    means = values[idx].mean(axis=1)
    return (float(np.percentile(means, 2.5)),
            float(np.percentile(means, 97.5)))


def score_corpus(peer_docs: Sequence[Sequence[str]],
                 model_docs: Sequence[Sequence[str]],
                 n_bootstrap: int = 1000) -> Dict[str, Dict[str, float]]:
    """Corpus scores in pyrouge's results_dict key layout
    (decode.py:283-289 reads `<metric>_f_score` / `_recall` / `_precision`
    plus `_cb`/`_ce` CI bounds)."""
    if len(peer_docs) != len(model_docs):
        raise ValueError(
            f"{len(peer_docs)} decoded vs {len(model_docs)} reference docs")
    per_doc: Dict[str, Dict[str, List[float]]] = {
        m: {"f_score": [], "recall": [], "precision": []}
        for m in ("rouge_1", "rouge_2", "rouge_l")}
    for peer, model in zip(peer_docs, model_docs):
        doc = score_document(peer, model)
        for m, s in doc.items():
            per_doc[m]["f_score"].append(s.f)
            per_doc[m]["recall"].append(s.recall)
            per_doc[m]["precision"].append(s.precision)
    results: Dict[str, Dict[str, float]] = {}
    for m, stats in per_doc.items():
        results[m] = {}
        for stat, vals in stats.items():
            arr = np.asarray(vals, dtype=np.float64)
            mean = float(arr.mean()) if len(arr) else 0.0
            lo, hi = _bootstrap_ci(arr, n_samples=n_bootstrap)
            results[m][stat] = mean
            results[m][f"{stat}_cb"] = lo
            results[m][f"{stat}_ce"] = hi
    return results


# --------------------------------------------------------------------------
# pyrouge-layout directory evaluation (decode.py:187-222, 268-301)
# --------------------------------------------------------------------------

def read_summary_file(path: str) -> List[str]:
    """One sentence per line (write_for_rouge layout, decode.py:202-211)."""
    with open(path, "r", encoding="utf-8") as f:
        return [line.rstrip("\n") for line in f if line.strip()]


def rouge_eval(ref_dir: str, dec_dir: str,
               n_bootstrap: int = 1000) -> Dict[str, Dict[str, float]]:
    """Evaluate the write_for_rouge file layout: `ref_dir/<i>_reference.txt`
    vs `dec_dir/<i>_decoded.txt` (decode.py:215-221 naming)."""
    refs = sorted(glob.glob(os.path.join(ref_dir, "*_reference.txt")))
    peers, models = [], []
    for ref_path in refs:
        stem = os.path.basename(ref_path).split("_")[0]
        dec_path = os.path.join(dec_dir, f"{stem}_decoded.txt")
        if not os.path.exists(dec_path):
            raise FileNotFoundError(f"missing decoded file {dec_path}")
        models.append(read_summary_file(ref_path))
        peers.append(read_summary_file(dec_path))
    return score_corpus(peers, models, n_bootstrap=n_bootstrap)


def rouge_log(results_dict: Dict[str, Dict[str, float]],
              dir_to_write: str) -> str:
    """Format + log + write ROUGE_results.txt (decode.py:280-301)."""
    lines = []
    for x in ("1", "2", "l"):
        lines.append(f"\nROUGE-{x}:")
        for y in ("f_score", "recall", "precision"):
            key = f"rouge_{x}"
            val = results_dict[key][y]
            cb = results_dict[key][f"{y}_cb"]
            ce = results_dict[key][f"{y}_ce"]
            lines.append(
                f"{key}_{y}: {val:.4f} with confidence interval "
                f"({cb:.4f}, {ce:.4f})")
    text = "\n".join(lines)
    log.info(text)
    os.makedirs(dir_to_write, exist_ok=True)
    results_file = os.path.join(dir_to_write, "ROUGE_results.txt")
    log.info("Writing final ROUGE results to %s...", results_file)
    with open(results_file, "w", encoding="utf-8") as f:
        f.write(text)
    return text
