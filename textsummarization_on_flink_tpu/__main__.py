from textsummarization_on_flink_tpu.cli import main

raise SystemExit(main())
