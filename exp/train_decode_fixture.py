"""Train the decode-bench fixture: reference-scale params that actually
emit STOP (VERDICT r4 weak #1 — random init never finishes, so the
decode rows could only measure the all-100-steps worst case, and the
while/chunked early-exit A/B measured pure overhead).

Task: synthetic copy data — the target is the article's token prefix,
length L ~ uniform(min_dec_steps, 70), terminated by STOP.  A few
hundred CPU steps teach (a) copy-attention onto the article and (b) a
position-dependent STOP hazard, so beam search on the bench's random
articles finishes at article-dependent steps in the realistic band
instead of never.  The fixture file itself stays untracked (tens of MB;
this script is the committed recipe — bench.py's BENCH_MODE=decode
auto-loads the npz when present, see bench._decode_params_spec):

    JAX_PLATFORMS=cpu nice -n 19 python exp/train_decode_fixture.py \
        [--family pointer_generator] [--steps 800] [--coverage-steps 80]

Calibration note (2026-07-31): at 300 steps the beam stops at the
36-step min_dec_steps floor (weak copy confidence makes STOP dominate
as soon as allowed); at 800 steps (~2h CPU, loss ~2.6) it holds on to
44 generated steps — a learned, mid-band stopping point.

Writes exp/decode_fixture_<family>.npz (keystr -> array, the layout
bench._load_decode_fixture validates leaf-for-leaf) and prints the
generated-step distribution the trained fixture produces under the real
beam search at the bench's exact serving config.
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.vocab import STOP_ID  # noqa: E402


def synth_copy_batch(hps, rng):
    """Training arrays for the copy task (same key layout as
    __graft_entry__._example_arrays, but with learnable targets)."""
    from __graft_entry__ import _example_arrays

    arrays = _example_arrays(hps, rng)
    B, T_dec = hps.batch_size, hps.max_dec_steps
    # generated length (incl. STOP) in the realistic serving band
    lengths = rng.randint(hps.min_dec_steps, 71, size=(B,))
    dec = np.zeros((B, T_dec), np.int32)
    tgt = np.zeros((B, T_dec), np.int32)
    mask = np.zeros((B, T_dec), np.float32)
    from textsummarization_on_flink_tpu.data.vocab import START_ID
    for b in range(B):
        L = int(lengths[b])
        prefix = arrays["enc_batch"][b, : L - 1]
        dec[b, 0] = START_ID
        dec[b, 1:L] = prefix[: L - 1]
        tgt[b, : L - 1] = prefix
        tgt[b, L - 1] = STOP_ID
        mask[b, :L] = 1.0
    arrays["dec_batch"] = dec
    arrays["target_batch"] = tgt
    arrays["dec_padding_mask"] = mask
    return arrays


def train(family_name, steps, coverage_steps, seed=0):
    import jax

    from textsummarization_on_flink_tpu.train import trainer as trainer_lib

    rng = np.random.RandomState(seed)
    base = dict(batch_size=16, mode="train", model_family=family_name)
    hps = HParams(coverage=False, **base)
    state = trainer_lib.init_train_state(hps, hps.vocab_size, seed=seed)
    phases = [(hps, steps)]
    if family_name == "pointer_generator" and coverage_steps:
        # the decode bench runs pg with coverage=True (reference serving
        # config): convert and fine-tune like run_summarization.py's
        # convert_to_coverage_model path
        phases.append((HParams(coverage=True, **base), coverage_steps))

    for phase_hps, n in phases:
        if phase_hps.coverage and "w_c" not in str(
                jax.tree_util.tree_structure(state.params)):
            from textsummarization_on_flink_tpu.models import (
                pointer_generator as pg,
            )

            state = state._replace(params=pg.add_coverage_params(
                state.params, jax.random.PRNGKey(seed + 1)))
            state = trainer_lib.init_train_state(
                phase_hps, phase_hps.vocab_size, seed=seed,
                params=state.params)
        step_fn = jax.jit(trainer_lib.make_train_step(phase_hps), donate_argnums=0)
        t0 = time.time()
        for i in range(n):
            arrays = synth_copy_batch(phase_hps, rng)
            state, metrics = step_fn(state, arrays)
            if i % 20 == 0 or i == n - 1:
                loss = float(jax.device_get(metrics.loss))
                print(f"[fixture] coverage={phase_hps.coverage} "
                      f"step {i + 1}/{n} loss {loss:.3f} "
                      f"({time.time() - t0:.0f}s)", flush=True)
    return state.params


def evaluate(params, family_name):
    """Generated-step distribution under the real beam search at the
    decode bench's exact serving config and input arrays."""
    import jax

    from __graft_entry__ import _example_arrays
    from textsummarization_on_flink_tpu.decode import beam_search

    hps = HParams(batch_size=4, mode="decode",
                  coverage=family_name != "transformer",
                  model_family=family_name)
    arrays = _example_arrays(hps, np.random.RandomState(0))
    arrays = {k: v for k, v in arrays.items()
              if not k.startswith(("dec_", "target_"))}
    out = beam_search.run_beam_search_jit(params, hps, arrays,
                                          loop="while", chunk=None)
    gen = sorted(int(x) - 1 for x in np.asarray(jax.device_get(out.length)))
    print(f"[fixture] gen_steps per article: {gen} "
          f"(band target: {hps.min_dec_steps}-70, max {hps.max_dec_steps})")
    return gen


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", default="pointer_generator")
    ap.add_argument("--steps", type=int, default=800)
    ap.add_argument("--coverage-steps", type=int, default=80)
    ap.add_argument("--out", default=None)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import jax

    params = train(args.family, args.steps, args.coverage_steps, args.seed)
    gen = evaluate(params, args.family)
    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"decode_fixture_{args.family}.npz")
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    np.savez(out, **{jax.tree_util.keystr(k): np.asarray(v)
                     for k, v in flat})
    print(f"[fixture] wrote {out} "
          f"({os.path.getsize(out) / 1e6:.1f} MB); decode bench will "
          f"auto-load it (bench._decode_params_spec)")
    if all(g >= 99 for g in gen):
        print("[fixture] WARNING: no article finished early — train "
              "longer (--steps) before trusting decode early-exit rows")


if __name__ == "__main__":
    main()
