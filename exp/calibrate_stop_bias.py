"""Calibrate BENCH_STOP_BIAS: find an output-projection STOP-logit bias
that makes random-init params finish beam search in a realistic band
(gen_steps_p50 ~ 40-65 against min_dec_steps=35 / max_dec_steps=100),
so the decode bench measures real early-exit behaviour instead of the
all-beams-run-100-steps worst case (VERDICT r4 weak #1).

Run:  JAX_PLATFORMS=cpu nice -n 19 python exp/calibrate_stop_bias.py [family]
"""
import sys

import jax
import numpy as np

sys.path.insert(0, ".")
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.decode import beam_search
from textsummarization_on_flink_tpu.models import get_family
from __graft_entry__ import _example_arrays

family_name = sys.argv[1] if len(sys.argv) > 1 else "pointer_generator"
hps = HParams(batch_size=4, mode="decode",
              coverage=family_name != "transformer",
              model_family=family_name)
family = get_family(hps.model_family)
base = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(0))
arrays = _example_arrays(hps, np.random.RandomState(0))
arrays = {k: v for k, v in arrays.items()
          if not k.startswith(("dec_", "target_"))}


def with_bias(params, b):
    # the SAME bias application the decode bench uses — calibrating a
    # different code path would make the calibrated default meaningless
    import bench

    return bench._stop_biased(params, hps.vocab_size, b)


for b in [float(x) for x in (sys.argv[2:] or
                             [0.0, 0.5, 1.0, 2.0, 4.0, 8.0])]:
    out = beam_search.run_beam_search_jit(with_bias(base, b), hps, arrays,
                                          loop="while", chunk=None)
    lengths = np.asarray(jax.device_get(out.length))
    print(f"bias={b:6.2f}  gen_steps={sorted(int(x) - 1 for x in lengths)}"
          f"  p50={int(np.median(lengths)) - 1}", flush=True)
