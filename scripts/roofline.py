#!/usr/bin/env python
"""Roofline lower bounds for the sweep's train configs, from XLA's own
cost model.

    python scripts/roofline.py [--configs train_b16,train_b64,...]
                               [--chip v5e] [--json]
                               [--bench BENCH_ALL.jsonl]

For each config this compiles the REAL train step on the current
backend (CPU works: HLO flop counts are backend-portable; bytes
accessed depends on fusion decisions, so treat it as an estimate) and
reports:

  * flops/step from XLA `cost_analysis()` next to the analytic model
    `bench.py` uses for MFU (a big disagreement means one of them is
    wrong — that cross-check is the point of printing both);
  * bytes accessed/step and arithmetic intensity;
  * the compute floor (flops / peak bf16) and bandwidth floor
    (bytes / peak HBM) on the target chip, whichever is larger being
    the minimum achievable step time, with the implied max samples/s;
  * the measured step time from BENCH_ALL.jsonl when a live record
    with the matching run tag exists (measured/floor says how much of
    the gap is left for dispatch latency and scan overhead).

Why it exists (VERDICT r3 #4): an MFU number alone ("3.1%") reads as an
indictment; the roofline says how much of that is physics.  E.g. at
reference scale the pointer-generator step accesses ~12 GB — a ~15 ms
bandwidth floor on one v5e regardless of FLOPs — so the measured 29 ms
step was within 2x of the memory roofline, and the remaining levers
(unroll, bf16 streams) attack bytes and scan latency, not FLOPs.

The reference has no counterpart: its only instrumentation is per-step
wall clock (run_summarization.py:223-226) on a CPU-pinned graph.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# per-chip (peak bf16 TFLOP/s, peak HBM GB/s) — public TPU specs
CHIPS = {
    "v4": (275.0, 1228.0),
    "v5e": (197.0, 819.0),
    "v5p": (459.0, 2765.0),
    "v6e": (918.0, 1640.0),
}

# sweep-row tag -> the SAME env mapping scripts/bench_all.sh uses; the
# actual shapes come from bench._preset_overrides via hps_for(), so the
# roofline always describes exactly the config the sweep measures (no
# hand-duplicated values to drift).  train_tiny exists for fast tests
# (unroll=1: tracing cost scales with the unrolled scan body; the
# flop/byte counts are unroll-invariant).
CONFIGS = {
    "train_b16": {},
    "train_b16_remat": {"BENCH_REMAT": "1"},
    "train_b64": {"BENCH_BATCH": "64"},
    "train_scaled": {"BENCH_PRESET": "scaled"},
    "train_transformer": {"BENCH_FAMILY": "transformer"},
    "train_tiny": {"BENCH_PRESET": "tiny", "BENCH_BATCH": "4",
                   "BENCH_UNROLL": "1"},
    # byte-diet lever rows (ISSUE 5, PERF.md "Byte diet"): streaming
    # chunked vocab loss, bf16 optimizer state, and both together — the
    # roofline's bytes column is the CPU-verifiable side of each claim
    "train_b16_losschunk": {"BENCH_LOSS_CHUNK": "25"},
    "train_b16_optbf16": {"BENCH_OPT_DTYPE": "bfloat16"},
    "train_b16_bytediet": {"BENCH_LOSS_CHUNK": "25",
                           "BENCH_OPT_DTYPE": "bfloat16"},
    "train_transformer_losschunk": {"BENCH_FAMILY": "transformer",
                                    "BENCH_LOSS_CHUNK": "25"},
}

# decode lever configs (ISSUE 7, PERF.md "Decode byte diet"): the
# compiled beam search's bytes per emitted token + peak temp via
# __graft_entry__.decode_step_cost — batch path (the auto 'chunked'
# loop) and one step_slots_jit slot chunk per family, plus a tiny row
# for the repro smoke.  The committed gate-scale reductions live in
# BYTE_BUDGET.json's decode section; these rows put the ask-scale
# numbers in the sweep record like the train lever rows above.
DECODE_CONFIGS = {
    "decode_bytes_pg": {"env": {}, "path": "batch"},
    "decode_bytes_pg_slot": {"env": {}, "path": "slot"},
    "decode_bytes_transformer": {"env": {"BENCH_FAMILY": "transformer"},
                                 "path": "batch"},
    "decode_bytes_transformer_slot": {
        "env": {"BENCH_FAMILY": "transformer"}, "path": "slot"},
    "decode_bytes_tiny": {"env": {"BENCH_PRESET": "tiny",
                                  "BENCH_BATCH": "2", "BENCH_UNROLL": "1"},
                          "path": "batch"},
}

# speculative-tier FLOPs rows (ISSUE 10, PERF.md "Speculative tier"):
# per-tier FLOPs per emitted token via __graft_entry__.decode_step_flops
# (beam / greedy / AAN draft, plus the transformer's parallel verify) —
# the draft-cost side of BYTE_BUDGET.json's spec section at ask scale.
SPEC_CONFIGS = {
    "spec_flops_pg": {"env": {}},
    "spec_flops_transformer": {"env": {"BENCH_FAMILY": "transformer"}},
}

_BENCH_ENV_VARS = ("BENCH_BATCH", "BENCH_PRESET", "BENCH_FAMILY",
                   "BENCH_UNROLL", "BENCH_REMAT", "BENCH_LOSS_CHUNK",
                   "BENCH_OPT_DTYPE")

# lever row -> the config its byte reduction is measured against
_BYTE_DIET_BASELINES = {
    "train_b16_losschunk": "train_b16",
    "train_b16_optbf16": "train_b16",
    "train_b16_bytediet": "train_b16",
    "train_transformer_losschunk": "train_transformer",
}


def hps_for(tag: str, bench_mod):
    """The exact HParams the sweep row measures: bench_all.sh's env
    mapping + bench.bench_train's own construction."""
    from textsummarization_on_flink_tpu.config import HParams

    if tag in DECODE_CONFIGS:
        env = DECODE_CONFIGS[tag]["env"]
    elif tag in SPEC_CONFIGS:
        env = SPEC_CONFIGS[tag]["env"]
    else:
        env = CONFIGS[tag]
    saved = {k: os.environ.pop(k, None) for k in _BENCH_ENV_VARS}
    try:
        os.environ.update(env)
        batch = int(os.environ.get("BENCH_BATCH", "16"))
        hps = HParams(batch_size=batch, compute_dtype="bfloat16",
                      **bench_mod._preset_overrides())
        if tag in SPEC_CONFIGS:
            # the committed REFERENCE-scale draft recipe (BYTE_BUDGET.json
            # spec.ref_overrides: 1 kept layer, H/2-wide narrow draft,
            # rank-64 factored head — ISSUE 12), spec_k from the HParams
            # default; read from the budget so this row and the gate can
            # never describe two different drafts
            budget_path = os.path.join(REPO, "BYTE_BUDGET.json")
            with open(budget_path, encoding="utf-8") as f:
                ref_overrides = json.load(f)["spec"]["ref_overrides"]
            return hps.replace(mode="decode", **ref_overrides)
        return hps.replace(mode="decode") if tag in DECODE_CONFIGS else hps
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("bench", mod)
    spec.loader.exec_module(mod)
    return mod


def cost_of_train_step(hps):
    """Compile the real train step and return XLA's {flops, bytes,
    temp_bytes} — through the ONE shared compile-and-read helper
    (__graft_entry__.train_step_cost), same as bench.py's bytes mode and
    the tier-1 byte gate."""
    from __graft_entry__ import train_step_cost

    return train_step_cost(hps)


def analyze(tag: str, chip: str, bench_mod, measured: dict | None):
    hps = hps_for(tag, bench_mod)
    cost = cost_of_train_step(hps)
    analytic = (bench_mod.transformer_flops_per_step(hps)
                if hps.model_family == "transformer"
                else bench_mod.train_flops_per_step(hps))
    peak_tflops, peak_gbps = CHIPS[chip]
    t_compute = cost["flops"] / (peak_tflops * 1e12)
    t_bw = cost["bytes"] / (peak_gbps * 1e9)
    floor = max(t_compute, t_bw)
    rec = {
        "config": tag,
        "chip": chip,
        "batch": hps.batch_size,
        "xla_flops": cost["flops"],
        "analytic_flops": analytic,
        "flops_ratio_xla_over_analytic": round(cost["flops"] / analytic, 2),
        "bytes_accessed": cost["bytes"],
        "arith_intensity_flops_per_byte": round(
            cost["flops"] / max(cost["bytes"], 1.0), 2),
        "compute_floor_ms": round(t_compute * 1e3, 3),
        "bandwidth_floor_ms": round(t_bw * 1e3, 3),
        "min_step_ms": round(floor * 1e3, 3),
        "bound": "bandwidth" if t_bw >= t_compute else "compute",
        "max_samples_per_sec": round(hps.batch_size / floor, 1),
    }
    if measured is not None:
        ms = measured.get("step_time_ms")
        if ms:
            rec["measured_step_ms"] = ms
            rec["measured_over_floor"] = round(ms / rec["min_step_ms"], 2)
            rec["measured_at"] = measured.get("captured_at")
    return rec


def analyze_decode(tag: str, chip: str, bench_mod):
    """A decode-bytes row: bytes/token + peak temp of the compiled beam
    search, with the chip's bandwidth floor per emitted token (the
    decode analogue of the train rows' min_step_ms)."""
    from textsummarization_on_flink_tpu.config import beam_chunk_from_env
    from __graft_entry__ import decode_step_cost

    hps = hps_for(tag, bench_mod)
    path = DECODE_CONFIGS[tag]["path"]
    chunk = min(beam_chunk_from_env(), hps.max_dec_steps)
    cost = decode_step_cost(hps, loop="chunked" if path == "batch" else "scan",
                            chunk=chunk, path=path)
    _, peak_gbps = CHIPS[chip]
    t_bw_token = cost["bytes_per_token"] / (peak_gbps * 1e9)
    return {
        "config": tag,
        "chip": chip,
        "path": path,
        "batch": hps.batch_size,
        "family": hps.model_family,
        "chunk": chunk,
        "bytes_accessed": cost["bytes"],
        "bytes_per_token": round(cost["bytes_per_token"], 1),
        "temp_bytes": cost["temp_bytes"],
        "bandwidth_floor_us_per_token": round(t_bw_token * 1e6, 3),
        "max_tokens_per_sec": round(1.0 / max(t_bw_token, 1e-12), 1),
        "note": "HloCostAnalysis single-counts the decode loop body, so "
                "bytes/token tracks per-step traffic + loop-invariant "
                "overhead; committed gate-scale reductions live in "
                "BYTE_BUDGET.json decode",
    }


def analyze_spec(tag: str, chip: str, bench_mod):
    """A spec-tier FLOPs row: per-tier step FLOPs per emitted token
    (cost-analysis + the closed-form analytic model), the draft/full
    ratio, and the acceptance->expected-speedup curve the committed
    BYTE_BUDGET.json spec section models."""
    from __graft_entry__ import decode_step_flops

    hps = hps_for(tag, bench_mod)
    peak_tflops, _ = CHIPS[chip]
    flops = decode_step_flops(hps)
    tiers = {
        name: {
            "flops_per_token": c["flops"],
            "analytic_flops_per_token": c["analytic_flops"],
            "state_bytes": c["state_bytes"],
            "compute_floor_us_per_token": round(
                c["flops"] / (peak_tflops * 1e12) * 1e6, 4),
        }
        for name, c in flops["tiers"].items()
    }
    return {
        "config": tag,
        "chip": chip,
        "family": hps.model_family,
        "spec_k": flops["spec_k"],
        "draft_dec_layers": hps.draft_dec_layers or hps.dec_layers,
        "tiers": tiers,
        "draft_full_flops_ratio": round(flops["draft_full_ratio"], 4),
        "draft_state_ratio": round(flops["draft_state_ratio"], 4),
        "verify_flops_per_position": flops["verify_flops_per_position"],
        "expected_speedup_vs_acceptance": {
            a: round(s, 4) for a, s in flops["expected_speedup"].items()},
        "note": "speedup model: one verify invocation ~ one full step "
                "(bandwidth-bound weight streaming); committed ceilings "
                "+ kill conditions in BYTE_BUDGET.json spec",
    }


def _cost_of(fn, *args):
    import jax

    ca = jax.jit(fn).lower(*args).compile().cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0]
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes": float(ca.get("bytes accessed", 0.0))}


def attribution_of(hps, full_step_cost=None):
    """Where the step's bytes go, by phase: compile forward-only and
    forward+backward, and diff against the full optimizer step —
    backward = grad − forward, optimizer = step − grad.  Pass the
    already-compiled full-step cost (analyze() has it) to avoid
    recompiling the most expensive program.

    Caveat on every (diff) row's BYTES: each phase is an independently
    compiled program, and a standalone subprogram must materialize
    outputs the bigger program may fuse away — so a diff can come out
    low or even negative when fusion overlaps phases.  Flop diffs don't
    have this problem (flop counts are fusion-independent).  The table
    marks negative byte diffs as fusion overlap."""
    import numpy as np

    import jax

    from textsummarization_on_flink_tpu.models import get_family
    from textsummarization_on_flink_tpu.train import trainer as trainer_lib
    from __graft_entry__ import _example_arrays

    family = get_family(hps.model_family)
    state = trainer_lib.init_train_state(hps, hps.vocab_size, seed=0)
    arrays = _example_arrays(hps, np.random.RandomState(0))

    def fwd(params, arrays):
        out = family.forward_train(params, hps, arrays)
        return out.total_loss if hps.coverage else out.loss

    if full_step_cost is None:
        full_step_cost = cost_of_train_step(hps)
    phases = {
        "forward": _cost_of(fwd, state.params, arrays),
        "fwd+bwd": _cost_of(lambda p, a: jax.grad(fwd)(p, a),
                            state.params, arrays),
        "full step": dict(full_step_cost),
    }
    if hps.model_family == "pointer_generator":
        # the pg family has a clean encoder seam (models.pointer_generator
        # .encode); the remainder of forward is the decoder scan + the
        # vocab projection + loss
        from textsummarization_on_flink_tpu.models import (
            pointer_generator as pg,
        )

        enc = _cost_of(
            lambda p, a: pg.encode(p, hps, a["enc_batch"], a["enc_lens"],
                                   a["enc_padding_mask"]),
            state.params, arrays)
        phases["encoder fwd"] = enc
        phases["dec+loss fwd (diff)"] = {
            k: phases["forward"][k] - enc[k] for k in ("flops", "bytes")}
    phases["backward (diff)"] = {
        k: phases["fwd+bwd"][k] - phases["forward"][k]
        for k in ("flops", "bytes")}
    phases["optimizer (diff)"] = {
        k: phases["full step"][k] - phases["fwd+bwd"][k]
        for k in ("flops", "bytes")}
    return phases


def measured_rows(path: str) -> dict:
    """Newest live measurement per run tag (bench_latest's definition)."""
    if not os.path.exists(path):
        return {}
    from bench_latest import latest_by_tag

    return {tag: rec for tag, rec in latest_by_tag(path).items()
            if "error" not in rec and not rec.get("stale")}


def main(argv=None):
    ap = argparse.ArgumentParser()
    default_cfgs = ("train_b16,train_b16_remat,train_b64,train_scaled,"
                    "train_transformer,train_b16_losschunk,"
                    "train_b16_optbf16,train_b16_bytediet,"
                    "train_transformer_losschunk,"
                    "decode_bytes_pg,decode_bytes_pg_slot,"
                    "decode_bytes_transformer,decode_bytes_transformer_slot,"
                    "spec_flops_pg,spec_flops_transformer")
    ap.add_argument("--configs", default=default_cfgs)
    ap.add_argument("--chip", default="v5e", choices=sorted(CHIPS))
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--bench", default=os.path.join(REPO, "BENCH_ALL.jsonl"))
    ap.add_argument("--attribute", action="store_true",
                    help="also compile forward and fwd+bwd per config "
                         "(full-step cost is reused) and report the "
                         "per-phase flop/byte split")
    args = ap.parse_args(argv)

    bench_mod = _load_bench()
    measured = measured_rows(args.bench)
    out = []
    decode_out = []
    spec_out = []
    for tag in args.configs.split(","):
        tag = tag.strip()
        if tag in DECODE_CONFIGS:
            print(f"[roofline] compiling {tag} ...", file=sys.stderr)
            decode_out.append(analyze_decode(tag, args.chip, bench_mod))
            continue
        if tag in SPEC_CONFIGS:
            print(f"[roofline] compiling {tag} ...", file=sys.stderr)
            spec_out.append(analyze_spec(tag, args.chip, bench_mod))
            continue
        if tag not in CONFIGS:
            raise SystemExit(f"unknown config {tag!r}; "
                             f"choose from {sorted(CONFIGS)}, "
                             f"{sorted(DECODE_CONFIGS)}, or "
                             f"{sorted(SPEC_CONFIGS)}")
        print(f"[roofline] compiling {tag} ...", file=sys.stderr)
        rec = analyze(tag, args.chip, bench_mod, measured.get(tag))
        if args.attribute:
            rec["attribution"] = attribution_of(
                hps_for(tag, bench_mod),
                full_step_cost={"flops": rec["xla_flops"],
                                "bytes": rec["bytes_accessed"]})
        out.append(rec)
    if args.json:
        for rec in out + decode_out + spec_out:
            print(json.dumps(rec))
        return 0
    hdr = (f"{'config':<18} {'bound':<9} {'GFLOP':>8} {'GB':>7} "
           f"{'floor ms':>8} {'max smp/s':>9} {'measured':>9}")
    print(f"roofline on one {args.chip} "
          f"({CHIPS[args.chip][0]:.0f} bf16 TFLOP/s, "
          f"{CHIPS[args.chip][1]:.0f} GB/s HBM)")
    if out:
        print(hdr)
    for r in out:
        meas = (f"{r['measured_step_ms']:.1f}ms"
                if "measured_step_ms" in r else "-")
        print(f"{r['config']:<18} {r['bound']:<9} "
              f"{r['xla_flops'] / 1e9:>8.1f} "
              f"{r['bytes_accessed'] / 1e9:>7.2f} "
              f"{r['min_step_ms']:>8.2f} "
              f"{r['max_samples_per_sec']:>9.0f} {meas:>9}")
    if decode_out:
        print("\ndecode byte accounting (loop body single-counted; "
              "committed reductions in BYTE_BUDGET.json decode):")
        print(f"{'config':<30} {'path':<6} {'KB/token':>9} "
              f"{'peak temp MB':>13} {'floor us/tok':>13}")
        for r in decode_out:
            temp = (f"{r['temp_bytes'] / 1e6:.1f}"
                    if r["temp_bytes"] is not None else "-")
            print(f"{r['config']:<30} {r['path']:<6} "
                  f"{r['bytes_per_token'] / 1e3:>9.1f} {temp:>13} "
                  f"{r['bandwidth_floor_us_per_token']:>13.3f}")
    if spec_out:
        print("\nspeculative-tier FLOPs per emitted token "
              "(committed ceilings in BYTE_BUDGET.json spec):")
        print(f"{'config':<24} {'tier':<7} {'kFLOP/tok':>10} "
              f"{'analytic':>9} {'state B':>8}")
        for r in spec_out:
            for name in ("beam", "greedy", "draft"):
                t = r["tiers"][name]
                print(f"{r['config']:<24} {name:<7} "
                      f"{t['flops_per_token'] / 1e3:>10.1f} "
                      f"{t['analytic_flops_per_token'] / 1e3:>9.1f} "
                      f"{t['state_bytes']:>8}")
            curve = ", ".join(
                f"a={a}:{s:.2f}" for a, s in
                r["expected_speedup_vs_acceptance"].items())
            print(f"  draft/full ratio {r['draft_full_flops_ratio']:.3f} "
                  f"(state {r['draft_state_ratio']:.4f}); "
                  f"expected speedup {curve}")
    by_tag = {r["config"]: r for r in out}
    diet_rows = [(tag, base) for tag, base in _BYTE_DIET_BASELINES.items()
                 if tag in by_tag and base in by_tag]
    if diet_rows:
        print("\nbyte-diet reductions (bytes accessed vs baseline config):")
        for tag, base in diet_rows:
            red = 1.0 - (by_tag[tag]["bytes_accessed"]
                         / by_tag[base]["bytes_accessed"])
            print(f"  {tag:<28} vs {base:<18} {red * 100:>6.1f}%")
    for r in out:
        if "attribution" in r:
            print(f"\n{r['config']} phase split (GB accessed / GFLOP):")
            for phase, c in r["attribution"].items():
                note = ("  [negative: fusion overlap between standalone-"
                        "compiled phases]" if c["bytes"] < 0 else "")
                print(f"  {phase:<17} {c['bytes'] / 1e9:>7.2f} GB  "
                      f"{c['flops'] / 1e9:>8.1f} GFLOP{note}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
