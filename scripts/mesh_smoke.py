"""One-mesh smoke (ISSUE 8): dp x tp train AND serve on the faked
8-device CPU mesh, end to end through the sharding registry.

  * train: the unified sharded step at dp=4 x tp=2 with every lever the
    registry composes — bf16 gradient wire annotation, --loss_chunk
    streaming vocab loss, bf16 Adagrad state — 3 real optimizer steps,
    finite losses, layouts preserved through the update.
  * serve: the SAME rows through BOTH serving engines at dp=2 x tp=2 —
    the micro-batch sharded beam search and the continuous slotted
    engine (resident state over dp, registry slot specs) — row-for-row
    identical to a single-device pass.

Wired into scripts/repro.sh (which exports the 8-device XLA flag); the
committed collective-byte claims live in BYTE_BUDGET.json's `comms`
section, enforced by tests/test_bytes_gate.py — this proves the paths
RUN, the gate proves what they move.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import tempfile  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.batching import (  # noqa: E402
    Batch,
    SummaryExample,
)
from textsummarization_on_flink_tpu.data.vocab import Vocab  # noqa: E402
from textsummarization_on_flink_tpu.parallel import mesh as mesh_lib  # noqa: E402
from textsummarization_on_flink_tpu.pipeline.io import (  # noqa: E402
    CollectionSink,
    CollectionSource,
)
from textsummarization_on_flink_tpu.serve.server import (  # noqa: E402
    ServingServer,
)
from textsummarization_on_flink_tpu.train import trainer  # noqa: E402


def train_smoke() -> None:
    hps = HParams(hidden_dim=8, emb_dim=6, batch_size=8, max_enc_steps=16,
                  max_dec_steps=6, beam_size=2, min_dec_steps=1,
                  vocab_size=64, max_oov_buckets=8,
                  dp=4, tp=2, grad_allreduce_dtype="bfloat16",
                  loss_chunk=3, opt_state_dtype="bfloat16")
    hps.validate()
    vocab = Vocab(words=[f"w{i}" for i in range(60)], max_size=64)
    rng = np.random.RandomState(0)
    exs = [SummaryExample.build(
        " ".join(rng.choice([f"w{j}" for j in range(50)], 8)),
        ["w1 w2 ."], vocab, hps) for _ in range(hps.batch_size)]
    batch = Batch(exs, hps, vocab)
    state = trainer.init_train_state(hps, vocab.size(), seed=0)
    plan = mesh_lib.make_mesh(hps)
    sharded = mesh_lib.shard_train_state(plan, state)
    step = mesh_lib.make_sharded_train_step(plan, donate=False)
    losses = []
    for _ in range(3):
        sharded, metrics = step(sharded, batch.as_arrays())
        losses.append(float(metrics.loss))
    assert all(np.isfinite(losses)), losses
    emb = sharded.params["embedding"]
    assert emb.sharding.spec == mesh_lib.P("tp", None), emb.sharding
    acc = jax.tree_util.tree_leaves(sharded.opt_state.accumulators)[0]
    assert acc.dtype == jnp.bfloat16, acc.dtype
    print(f"mesh train smoke OK: dp=4 x tp=2, bf16 wire + loss_chunk + "
          f"bf16 opt state, 3 steps, losses {['%.3f' % x for x in losses]}")


def serve_smoke() -> None:
    rows = [(f"uuid-{i}", f"article {i} .", "", f"reference {i} .")
            for i in range(8)]
    # 12 words + 4 specials = 16 ids: divisible by tp=2
    vocab = Vocab(words=["article", "reference", ".", "0", "1", "2", "3",
                         "4", "5", "6", "7", "x"])
    assert vocab.size() % 2 == 0, vocab.size()
    base = HParams(mode="decode", batch_size=2, hidden_dim=16, emb_dim=8,
                   vocab_size=vocab.size(), max_enc_steps=16,
                   max_dec_steps=6, beam_size=2, min_dec_steps=1,
                   max_oov_buckets=4, serve_max_wait_ms=50.0,
                   serve_max_queue=32)
    params = trainer.init_train_state(base, vocab.size(), seed=0).params

    def run(hps, tag):
        server = ServingServer(
            hps, vocab, params=params,
            decode_root=tempfile.mkdtemp(prefix=f"mesh_smoke_{tag}_"))
        sink = CollectionSink()
        with server:
            server.serve(CollectionSource(rows), sink)
        assert len(sink.rows) == 8, (tag, sink.rows)
        return {r[0]: r for r in sink.rows}

    want = run(base, "single")
    got_mb = run(base.replace(dp=2, tp=2), "mesh_microbatch")
    assert got_mb == want, "sharded micro-batch rows drifted"
    got_c = run(base.replace(dp=2, tp=2, serve_mode="continuous",
                             serve_slots=2, serve_refill_chunk=2),
                "mesh_continuous")
    assert got_c == want, "sharded continuous rows drifted"
    print("mesh serve smoke OK: dp=2 x tp=2 micro-batch AND continuous "
          "rows identical to single-device (8 rows each)")


def main() -> None:
    n = len(jax.devices())
    assert n >= 8, (
        f"mesh smoke needs the faked 8-device CPU mesh, have {n} "
        f"(export XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    train_smoke()
    serve_smoke()


if __name__ == "__main__":
    main()
