"""Live-plane smoke (ISSUE 9): start the obs HTTP exposition server,
drive a short continuous-serve run against the real tiny model while
scraping /metrics and /healthz, and assert the scrape is byte-identical
to ``obs.render_text()`` once the run quiesces.  Also proves the
request-trace path end to end: the run writes a unified events.jsonl
and ``scripts/trace_summary.py --request`` reconstructs one uuid's
timeline from it.

Fleet leg (ISSUE 15): a 2-replica FleetRouter with per-replica
registries is scraped on ``/fleet/metrics`` DURING a real run; once
quiesced, the merged ``serve_completed_total`` must equal the sum of
the two per-replica scrapes, and one ``/exemplars`` trace_id must
resolve to a reconstructable cross-replica timeline through
``trace_summary.py --request``.  Wired into scripts/repro.sh.
"""

import json
import os
import subprocess
import sys
import tempfile
import urllib.error
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from textsummarization_on_flink_tpu import obs  # noqa: E402
from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.vocab import Vocab  # noqa: E402
from textsummarization_on_flink_tpu.serve.fleet import (  # noqa: E402
    FleetRouter,
)
from textsummarization_on_flink_tpu.serve.server import (  # noqa: E402
    ServingServer,
)
from textsummarization_on_flink_tpu.train import trainer  # noqa: E402


def get(port: int, route: str, accept: str = ""):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{route}",
        headers={"Accept": accept} if accept else {})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, resp.read()


def scrape_value(body: bytes, name: str) -> float:
    """The UNLABELED series value of `name` in a text exposition."""
    for line in body.decode("utf-8").splitlines():
        if line.startswith(name + " "):
            return float(line.split()[1])
    raise AssertionError(f"{name} not in scrape")


def run_fleet_leg(hps, vocab, params) -> None:
    """The ISSUE 15 fleet leg: 2 replicas, own registries, one router;
    merged /fleet scrape == sum of per-replica scrapes, exemplar ->
    timeline."""
    events_dir = tempfile.mkdtemp(prefix="obs_http_smoke_fleet_")
    router_reg = obs.Registry()
    rep_regs = [obs.Registry(), obs.Registry()]
    sink = obs.install_event_sink(events_dir, flush_secs=0.1,
                                  reg=router_reg)
    replicas = [
        ServingServer(hps, vocab, params=params, registry=rep_regs[i],
                      decode_root=tempfile.mkdtemp(
                          prefix=f"obs_http_smoke_rep{i}_"))
        for i in range(2)]
    router = FleetRouter(replicas, hps, registry=router_reg)
    fleet_srv = obs.serve_http(0, router_reg)
    rep_srvs = [obs.serve_http(0, r) for r in rep_regs]
    try:
        with router:
            futs = [router.submit(f"article {i} .", uuid=f"fleet-{i}")
                    for i in range(8)]
            # the fleet plane must answer WHILE replicas decode
            status, live = get(fleet_srv.port, "/fleet/metrics")
            assert status == 200 and b"# TYPE" in live
            for f in futs:
                f.result(timeout=600)
            # quiesced (every future resolved, fleet still up): merged
            # counter == sum of the per-replica scrapes
            status, merged = get(fleet_srv.port, "/fleet/metrics")
            assert status == 200
            total = scrape_value(merged, "serve_completed_total")
            per_rep = []
            for srv in rep_srvs:
                _, body = get(srv.port, "/metrics")
                per_rep.append(scrape_value(body,
                                            "serve_completed_total"))
            assert total == sum(per_rep) == 8.0, (total, per_rep)
            assert all(v > 0 for v in per_rep), (
                f"least-loaded routing left a replica idle: {per_rep}")
            _, snap = get(fleet_srv.port, "/fleet/snapshot")
            fleet_snap = json.loads(snap)
            assert fleet_snap["replicas"] == ["router", "r0", "r1"], \
                fleet_snap["replicas"]
            assert fleet_snap["metrics"]["serve/completed_total"][
                "value"] == 8.0
            assert set(fleet_snap["health"]) == {"r0", "r1"}, \
                fleet_snap["health"]
            _, alerts = get(fleet_srv.port, "/alerts")
            payload = json.loads(alerts)
            assert payload["installed"] and payload["status"] == "ok", \
                payload
        # a STOPPED fleet retires its source map: /fleet/* answers 404
        # rather than serving (and memory-pinning) a dead fleet
        try:
            get(fleet_srv.port, "/fleet/metrics")
            raise AssertionError("/fleet/metrics served a stopped fleet")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        # one exemplar -> one reconstructable cross-replica timeline
        exemplar = None
        for srv in rep_srvs:
            _, body = get(srv.port, "/exemplars")
            for row in json.loads(body):
                if row["metric"].startswith("serve/e2e_latency_seconds"):
                    exemplar = row
                    break
            if exemplar:
                break
        assert exemplar is not None, "no e2e exemplar on either replica"
        out = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(__file__),
                                          "trace_summary.py"),
             events_dir, "--request", exemplar["trace_id"], "--json"],
            capture_output=True, text=True, check=True)
        tl = json.loads(out.stdout)
        stages = {e["event"] for e in tl["events"]}
        assert {"enqueue", "route", "resolve"} <= stages, stages
        assert tl["uuid"].startswith("fleet-"), tl["uuid"]
        replicas_seen = {e["replica"] for e in tl["events"]
                         if "replica" in e}
        assert replicas_seen, "no replica-tagged lifecycle events"
        print(f"obs http fleet smoke OK: merged {total:g} == "
              f"{'+'.join(f'{v:g}' for v in per_rep)}, exemplar "
              f"{exemplar['trace_id']} -> {tl['uuid']} "
              f"({sorted(stages)}, replicas {sorted(replicas_seen)})")
    finally:
        fleet_srv.close()
        for srv in rep_srvs:
            srv.close()
        sink.close()


def main() -> None:
    vocab = Vocab(words=["article", "reference", ".", "0", "1", "2", "3",
                         "4", "5", "6", "7"])
    hps = HParams(mode="decode", batch_size=2, hidden_dim=16, emb_dim=8,
                  vocab_size=vocab.size(), max_enc_steps=16, max_dec_steps=6,
                  beam_size=2, min_dec_steps=1, max_oov_buckets=4,
                  serve_mode="continuous", serve_slots=2,
                  serve_refill_chunk=2, serve_max_queue=32)
    params = trainer.init_train_state(hps, vocab.size(), seed=0).params

    # TS_SMOKE_OUT (ISSUE 16): a caller-named events dir, so repro.sh
    # can hand the run's events.jsonl straight to perf_report.py
    events_dir = os.environ.get("TS_SMOKE_OUT") or tempfile.mkdtemp(
        prefix="obs_http_smoke_")
    os.makedirs(events_dir, exist_ok=True)
    sink = obs.install_event_sink(events_dir, flush_secs=0.1)
    srv = obs.serve_http(0)  # ephemeral localhost port
    try:
        server = ServingServer(
            hps, vocab, params=params,
            decode_root=tempfile.mkdtemp(prefix="obs_http_smoke_dec_"))
        with server:
            futs = [server.submit(f"article {i} .", uuid=f"uuid-{i}")
                    for i in range(8)]
            # scrape DURING the loaded run: both endpoints must answer
            # while the dispatch thread is working
            status, live_metrics = get(srv.port, "/metrics")
            assert status == 200 and b"# TYPE" in live_metrics
            status, health = get(srv.port, "/healthz")
            payload = json.loads(health)
            assert payload["status"] in ("ok", "degraded"), payload
            assert "serve/dispatch" in payload["components"], payload
            for f in futs:
                f.result(timeout=600)
            # performance attribution plane (ISSUE 16): /profile must
            # answer on the live server with a non-empty phase table
            # and the committed compile warm set — 4 decode kernels
            # (init/pack/step/unpack) + one prefill per bucket USED
            status, prof_body = get(srv.port, "/profile")
            assert status == 200
            prof = json.loads(prof_body)
            assert prof["installed"], prof
            phase_names = {p["phase"] for p in prof["phases"]}
            assert {"serve/prefill", "serve/dispatch",
                    "serve/harvest"} <= phase_names, phase_names
            ledger = prof["compile_ledger"]
            sites = ledger["sites"]
            prefills = sites.get("decode/prefill_jit",
                                 {"compiles": 0})["compiles"]
            assert prefills >= 1, sites
            decode_kernels = sum(
                sites.get(k, {"compiles": 0})["compiles"]
                for k in ("decode/init_slots_jit", "decode/pack_slot_jit",
                          "decode/step_slots_jit",
                          "decode/unpack_slot_jit"))
            assert decode_kernels == 4, sites
            assert ledger["warm_set"] == 4 + prefills, ledger
            assert ledger["storm"] is None, ledger
            # the profiler's cached storm/divergence state rides the
            # /alerts scrape under the "profile" key
            _, alerts_body = get(srv.port, "/alerts")
            alerts = json.loads(alerts_body)
            assert alerts["profile"]["installed"], alerts
            assert alerts["profile"]["compile_storm"] is None, alerts
        # quiesced: an OpenMetrics-negotiated scrape must be
        # byte-identical to the in-process exposition (same counter
        # set, same values, exemplar annotations included); a plain
        # Prometheus-0.0.4 scrape must carry NO exemplar annotations
        # (a 0.0.4 parser would reject them)
        status, body = get(srv.port, "/metrics",
                           accept="application/openmetrics-text")
        assert status == 200
        rendered = obs.render_text(openmetrics=True).encode("utf-8")
        assert body == rendered, (
            f"scrape ({len(body)}B) != render_text ({len(rendered)}B)")
        _, plain = get(srv.port, "/metrics")
        assert b"trace_id" not in plain
        status, health = get(srv.port, "/healthz")
        payload = json.loads(health)
        # the stopped server RETIRED its beat — a finished component
        # must not pin /healthz at degraded
        assert "serve/dispatch" not in payload["components"], payload
        status, snap = get(srv.port, "/snapshot")
        snapshot = json.loads(snap)
        assert snapshot.get("serve/completed_total", {}).get("value") == 8.0
    finally:
        srv.close()
        sink.close()

    # one uuid's timeline back out of the unified events.jsonl
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__),
                                      "trace_summary.py"),
         events_dir, "--request", "uuid-3", "--json"],
        capture_output=True, text=True, check=True)
    tl = json.loads(out.stdout)
    stages = {e["event"] for e in tl["events"]}
    assert {"enqueue", "admit", "slot", "finish", "resolve"} <= stages, stages
    assert tl["phases"].get("total_ms") is not None, tl["phases"]
    print(f"obs http smoke OK: scrape == render_text "
          f"({len(body)} bytes), healthz {payload['status']} "
          f"({', '.join(sorted(payload['components']))}), uuid-3 timeline "
          f"{sorted(stages)} over {tl['phases']['total_ms']:.1f} ms, "
          f"/profile warm set {ledger['warm_set']} "
          f"(4 decode + {prefills} prefill), coverage "
          f"{prof['coverage']:.3f}")

    run_fleet_leg(hps, vocab, params)


if __name__ == "__main__":
    main()
