"""Live-plane smoke (ISSUE 9): start the obs HTTP exposition server,
drive a short continuous-serve run against the real tiny model while
scraping /metrics and /healthz, and assert the scrape is byte-identical
to ``obs.render_text()`` once the run quiesces.  Also proves the
request-trace path end to end: the run writes a unified events.jsonl
and ``scripts/trace_summary.py --request`` reconstructs one uuid's
timeline from it.  Wired into scripts/repro.sh.
"""

import json
import os
import subprocess
import sys
import tempfile
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from textsummarization_on_flink_tpu import obs  # noqa: E402
from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.vocab import Vocab  # noqa: E402
from textsummarization_on_flink_tpu.serve.server import (  # noqa: E402
    ServingServer,
)
from textsummarization_on_flink_tpu.train import trainer  # noqa: E402


def get(port: int, route: str):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{route}", timeout=10) as resp:
        return resp.status, resp.read()


def main() -> None:
    vocab = Vocab(words=["article", "reference", ".", "0", "1", "2", "3",
                         "4", "5", "6", "7"])
    hps = HParams(mode="decode", batch_size=2, hidden_dim=16, emb_dim=8,
                  vocab_size=vocab.size(), max_enc_steps=16, max_dec_steps=6,
                  beam_size=2, min_dec_steps=1, max_oov_buckets=4,
                  serve_mode="continuous", serve_slots=2,
                  serve_refill_chunk=2, serve_max_queue=32)
    params = trainer.init_train_state(hps, vocab.size(), seed=0).params

    events_dir = tempfile.mkdtemp(prefix="obs_http_smoke_")
    sink = obs.install_event_sink(events_dir, flush_secs=0.1)
    srv = obs.serve_http(0)  # ephemeral localhost port
    try:
        server = ServingServer(
            hps, vocab, params=params,
            decode_root=tempfile.mkdtemp(prefix="obs_http_smoke_dec_"))
        with server:
            futs = [server.submit(f"article {i} .", uuid=f"uuid-{i}")
                    for i in range(8)]
            # scrape DURING the loaded run: both endpoints must answer
            # while the dispatch thread is working
            status, live_metrics = get(srv.port, "/metrics")
            assert status == 200 and b"# TYPE" in live_metrics
            status, health = get(srv.port, "/healthz")
            payload = json.loads(health)
            assert payload["status"] in ("ok", "degraded"), payload
            assert "serve/dispatch" in payload["components"], payload
            for f in futs:
                f.result(timeout=600)
        # quiesced: the scrape must be byte-identical to the in-process
        # exposition (same counter set, same values)
        status, body = get(srv.port, "/metrics")
        assert status == 200
        rendered = obs.render_text().encode("utf-8")
        assert body == rendered, (
            f"scrape ({len(body)}B) != render_text ({len(rendered)}B)")
        status, health = get(srv.port, "/healthz")
        payload = json.loads(health)
        # the stopped server RETIRED its beat — a finished component
        # must not pin /healthz at degraded
        assert "serve/dispatch" not in payload["components"], payload
        status, snap = get(srv.port, "/snapshot")
        snapshot = json.loads(snap)
        assert snapshot.get("serve/completed_total", {}).get("value") == 8.0
    finally:
        srv.close()
        sink.close()

    # one uuid's timeline back out of the unified events.jsonl
    out = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__),
                                      "trace_summary.py"),
         events_dir, "--request", "uuid-3", "--json"],
        capture_output=True, text=True, check=True)
    tl = json.loads(out.stdout)
    stages = {e["event"] for e in tl["events"]}
    assert {"enqueue", "admit", "slot", "finish", "resolve"} <= stages, stages
    assert tl["phases"].get("total_ms") is not None, tl["phases"]
    print(f"obs http smoke OK: scrape == render_text "
          f"({len(body)} bytes), healthz {payload['status']} "
          f"({', '.join(sorted(payload['components']))}), uuid-3 timeline "
          f"{sorted(stages)} over {tl['phases']['total_ms']:.1f} ms")


if __name__ == "__main__":
    main()
