#!/bin/bash
# Standalone training launcher (reference run_train.sh parity:
# /root/reference/src/main/python/pointer-generator/run_train.sh).
python -m textsummarization_on_flink_tpu --mode=train --coverage=1 "$@"
