#!/usr/bin/env bash
# Fetch the processed CNN/DailyMail dataset (finished_files.zip: chunked
# tf.Example bins + vocab) — the same Google-Drive artifact the reference
# fetches (/root/reference/data/cnn-dailymail/download_data.sh:1-29).
#
# Google Drive's large-file confirm flow changes over time; this uses the
# current uuid/confirm form-token dance and is, like the reference script,
# "not guaranteed to work indefinitely".  If the fetch fails, download
# finished_files.zip manually (see data/cnn-dailymail/README.md in the
# reference for the dataset recipe) and unzip it into DEST.
#
# Usage: scripts/download_data.sh [DEST_DIR]   (default ./data/cnn-dailymail)
set -euo pipefail

FILE_ID='0BzQ6rtO2VN95a0c3TlZCWkl3aU0'
DEST="${1:-data/cnn-dailymail}"
ZIP="finished_files.zip"

mkdir -p "$DEST"
cd "$DEST"

fetch_gdrive() {
  local id="$1" out="$2" base='https://drive.google.com/uc?export=download'
  local cookies page token uuid
  cookies="$(mktemp)"
  page="$(mktemp)"
  curl -sc "$cookies" -L "${base}&id=${id}" -o "$page"
  # small files come straight through; large files return an HTML confirm
  # form carrying confirm= and uuid= tokens
  if grep -q 'download-form' "$page" 2>/dev/null; then
    token="$(grep -o 'name="confirm" value="[^"]*"' "$page" | cut -d'"' -f4 || true)"
    uuid="$(grep -o 'name="uuid" value="[^"]*"' "$page" | cut -d'"' -f4 || true)"
    curl -Lb "$cookies" -o "$out" \
      "https://drive.usercontent.google.com/download?id=${id}&export=download&confirm=${token:-t}&uuid=${uuid}"
  else
    mv "$page" "$out"
  fi
  rm -f "$cookies" "$page"
}

echo "Downloading ${ZIP} (CNN/DM finished_files) ..."
fetch_gdrive "$FILE_ID" "$ZIP"
unzip -o "$ZIP"
rm -f "$ZIP"
echo "Done: $(pwd)/finished_files"
echo "Train with: python -m textsummarization_on_flink_tpu --mode=train \\"
echo "  --data_path=$(pwd)/finished_files/chunked/train_* \\"
echo "  --vocab_path=$(pwd)/finished_files/vocab --log_root=log --exp_name=exp"
