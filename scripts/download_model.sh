#!/usr/bin/env bash
# Fetch the pretrained pointer-generator checkpoint
# (pretrained_model_tf1.2.1.zip) — the same Google-Drive artifact the
# reference fetches (/root/reference/log/download_model.sh:1-28) — and
# print the command that imports it into this framework's checkpoint
# format (checkpoint/tf1_import.py).
#
# Same caveat as download_data.sh: the Drive confirm flow is
# "not guaranteed to work indefinitely"; on failure download the zip
# manually and unzip into DEST.
#
# Usage: scripts/download_model.sh [DEST_DIR]   (default ./log)
set -euo pipefail

FILE_ID='0B7pQmm-OfDv7ZUhHZm9ZWEZidDg'
DEST="${1:-log}"
ZIP="pretrained_model_tf1.2.1.zip"

mkdir -p "$DEST"
cd "$DEST"

fetch_gdrive() {
  local id="$1" out="$2" base='https://drive.google.com/uc?export=download'
  local cookies page token uuid
  cookies="$(mktemp)"
  page="$(mktemp)"
  curl -sc "$cookies" -L "${base}&id=${id}" -o "$page"
  if grep -q 'download-form' "$page" 2>/dev/null; then
    token="$(grep -o 'name="confirm" value="[^"]*"' "$page" | cut -d'"' -f4 || true)"
    uuid="$(grep -o 'name="uuid" value="[^"]*"' "$page" | cut -d'"' -f4 || true)"
    curl -Lb "$cookies" -o "$out" \
      "https://drive.usercontent.google.com/download?id=${id}&export=download&confirm=${token:-t}&uuid=${uuid}"
  else
    mv "$page" "$out"
  fi
  rm -f "$cookies" "$page"
}

echo "Downloading ${ZIP} ..."
fetch_gdrive "$FILE_ID" "$ZIP"
unzip -o "$ZIP"
rm -f "$ZIP"

CKPT_DIR="$(pwd)/pretrained_model_tf1.2.1"
BUNDLE="$(ls "$CKPT_DIR"/*.index 2>/dev/null | head -1 | sed 's/\.index$//')"
echo "Done: $CKPT_DIR"
echo "Import into a servable train dir with:"
echo "  python -m textsummarization_on_flink_tpu.checkpoint.tf1_import \\"
echo "    ${BUNDLE:-$CKPT_DIR/<checkpoint-prefix>} log/exp/train"
