#!/usr/bin/env bash
# One-command, no-hardware validation of the whole framework:
#   scripts/repro.sh        # fast tier (~12 min): suite + dryrun + smokes
#   scripts/repro.sh full   # adds the slow test tier (~25 min total)
#
# Uses the virtual 8-device CPU mesh throughout; scrubs the TPU plugin
# off PYTHONPATH so a down tunnel can never hang an import (the axon
# registration hook wedges `import jax` otherwise).
set -euo pipefail
cd "$(dirname "$0")/.."

export PYTHONPATH="$PWD"
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"

echo "== lint (ruff or compileall fallback + tools/tslint AST rules)"
bash scripts/lint.sh

echo "== static analysis self-check (tslint JSON reporter + rule registry)"
# lint.sh already ran the text-mode gate; exercise the reporter paths it
# does NOT touch so a broken --format json / --list-rules fails repro
python -m tools.tslint --baseline tools/tslint/baseline.json --format json \
  > /dev/null
python -m tools.tslint --list-rules > /dev/null

echo "== telemetry smoke (obs registry/spans/exporters)"
python -m pytest tests/test_obs*.py -q -p no:cacheprovider

echo "== chaos smoke (resilience primitives + seeded fault injection)"
# fast, deterministic recovery-path checks (RESILIENCE.md); the full
# TS_FAULTS end-to-end sweeps live in scripts/chaos.sh
python -m pytest tests/test_resilience.py tests/test_chaos.py \
  tests/test_bridge.py -q -p no:cacheprovider

echo "== test suite"
# obs/chaos tests already ran in the smoke steps above — skip the rerun
OBS_SKIP=(--ignore=tests/test_obs.py --ignore=tests/test_obs_integration.py
          --ignore=tests/test_resilience.py --ignore=tests/test_chaos.py
          --ignore=tests/test_bridge.py)
if [ "${1:-fast}" = "full" ]; then
  python -m pytest tests/ -q "${OBS_SKIP[@]}"
else
  python -m pytest tests/ -q -m "not slow" "${OBS_SKIP[@]}"
fi

echo "== driver hooks: entry() trace + 8-device sharded dryrun"
python -c "
import jax, __graft_entry__ as g
fn, args = g.entry()
jax.jit(fn).lower(*args)
print('entry() traces ok')
g.dryrun_multichip(8)"

echo "== one-mesh smoke (dp x tp train + serve on the faked 8-device mesh)"
# the ISSUE-8 registry end to end: unified sharded step at dp=4 x tp=2
# with bf16 gradient wire + loss_chunk + bf16 opt state, then the same
# rows through BOTH serving engines at dp=2 x tp=2 with single-device
# row parity (the committed collective-byte claims live in
# BYTE_BUDGET.json's comms section, enforced in the suite above)
python scripts/mesh_smoke.py

echo "== serve smoke (CollectionSource -> ServingServer -> CollectionSink)"
# the concurrent serving path (SERVING.md) over the 8 synthetic rows,
# BOTH dispatch engines: micro-batch (queue admission, coalescing,
# bucket padding) and continuous — which now runs the ISSUE-11
# DISAGGREGATED path (mixed-length articles through the bucketed
# prefill stage into length-masked slots) — with row-for-row parity
# asserted between the two engines and the prefill telemetry checked
python scripts/serve_smoke.py

echo "== fleet smoke (3 replicas, kill one under load, exactly-once + parity)"
# the ISSUE-13 elastic fleet end to end on a real tiny model: the
# threaded FleetRouter fronts 3 in-process replicas, one is killed
# mid-decode, its residents/queued requests requeue on survivors, and
# the answers stay row-identical to a single-server run (the committed
# virtual-time swap/hedge/kill gates live in SERVE_SLO.json "fleet",
# enforced in the suite above)
python scripts/fleet_smoke.py

echo "== process-fleet smoke (3 OS child processes, SIGKILL mid-decode)"
# the ISSUE-17 process boundary end to end: the same router fronts 3
# SUPERVISED child processes (cli.py serve-replica) over the socket
# transport; one child is SIGKILLed on a real pid mid-decode, its
# orphans requeue on survivors (exactly-once + row parity vs the solo
# run), the victim restarts under supervision and is readmitted
# through the rotation breaker's half-open probe, and the survivors'
# events.jsonl ledgers witness every finish (the committed transport
# overhead ceilings live in SERVE_SLO.json process_fleet, enforced in
# the suite above; the armed serve.proc_kill sweep is in chaos.sh)
python scripts/fleet_smoke.py --transport=proc

echo "== locksan smoke (TS_LOCKSAN=1: runtime lock-order sanitizer armed)"
# the PR-18 dynamic half of tslint's concurrency story: the SAME
# process-fleet smoke (and one armed proc_kill chaos sweep) with every
# serve/resilience lock built through obs/locksan, cross-checked
# against the statically derived lock-order graph — an AB/BA inversion
# raises the typed LockOrderInversionError instead of deadlocking, and
# the smoke's _locksan_gate asserts acquisitions > 0 with ZERO
# inversions (ANALYSIS.md "Concurrency rules")
LG="$(mktemp /tmp/lockgraph.XXXXXX.json)"
python -m tools.tslint --lock-graph "$LG" textsummarization_on_flink_tpu tools
TS_LOCKSAN=1 TS_LOCKSAN_GRAPH="$LG" \
  python scripts/fleet_smoke.py --transport=proc
TS_LOCKSAN=1 TS_LOCKSAN_GRAPH="$LG" TS_FAULTS="serve.proc_kill:1.0:0:1" \
  python scripts/fleet_smoke.py --transport=proc
rm -f "$LG"

echo "== front-door smoke (coalescing + summary cache on a real model)"
# the ISSUE-14 front door end to end: a duplicate-heavy burst coalesces
# onto shared decodes, the warm pass serves byte-identical rows from
# the (content_hash, tier, fingerprint) cache with zero new decodes,
# and the tier axis misses as designed (the enforced zipf/tenant/fleet
# scheduling claims live in SERVE_SLO.json front_door, in the suite)
python scripts/front_door_smoke.py

echo "== hiersum smoke (framed long doc -> map-reduce fan-out -> append dedup)"
# the ISSUE-19 long-document path end to end on a real tiny model: a
# multi-chunk document arrives as framed rows through the pipeline
# stage (transform(hierarchical=True)), fans out chunk-by-chunk over a
# live ServingServer with one reduce pass, then an APPEND frame-set
# re-summarizes the grown document with every pre-append chunk served
# from the front-door cache — only the appended tail + one reduce
# decode (the committed fan-out makespan and cache-hit floor live in
# SERVE_SLO.json hierarchical, enforced in the suite above)
python scripts/hiersum_smoke.py

echo "== speculative-tier smoke (draft init -> spec decode -> exactness)"
# the ISSUE-10 fast path end to end: AAN draft mapped from the full
# model's own params, draft-then-verify decode through the decoder's
# tier surface, token exactness vs the greedy tier asserted (the
# committed FLOPs/state gates live in BYTE_BUDGET.json's spec section,
# enforced in the suite above)
python scripts/spec_smoke.py

echo "== distill-spec smoke (narrow draft distilled -> adaptive spec decode)"
# the ISSUE-12 fast path end to end: a tiny teacher trained on synthetic
# copy data, the NARROW draft (half width + factored vocab head)
# distilled from its greedy outputs through train/distill.DistillTrainer,
# then acceptance-adaptive spec decode asserted token-exact with greedy
# (the committed FLOPs-ratio and acceptance-floor gates live in
# BYTE_BUDGET.json's spec section, enforced in the suite above)
python scripts/spec_smoke.py --distill

echo "== live-plane smoke (/metrics + /healthz + /profile over a continuous run)"
# the ISSUE-9 exposition plane end to end: scrape-vs-render_text byte
# parity, healthz component heartbeats, one uuid's trace timeline
# reconstructed from the unified events.jsonl (trace_summary --request),
# and (ISSUE 16) the /profile phase table + compile-ledger warm set
# scraped off the live run.  TS_SMOKE_OUT keeps the events.jsonl for
# the perf-report stage below.
T="$(mktemp -d)"
trap 'rm -rf "$T"' EXIT
TS_SMOKE_OUT="$T/smoke_events" python scripts/obs_http_smoke.py

echo "== perf-report smoke (span self-time table off the smoke's events)"
# the ISSUE-16 offline attribution view: the same events.jsonl the
# trace timeline came from, aggregated per span name; the serve
# dispatch/prefill spans the run just produced must show up
python scripts/perf_report.py "$T/smoke_events" --json | python -c "
import json, sys
rep = json.load(sys.stdin)
rows = rep['spans']
names = {row['name'] for row in rows}
assert {'serve/dispatch', 'serve/prefill'} <= names, names
print(f'perf report OK: {len(rows)} span rows ({sorted(names)})')"

echo "== bench smokes (CPU, tiny): train / input / decode / serve"
for mode in train input decode serve; do
  BENCH_MODE="$mode" BENCH_PLATFORM=cpu BENCH_PRESET=tiny BENCH_STEPS=2 \
    BENCH_SECONDS=0.5 BENCH_SERVE_REQS=8 BENCH_SERVE_CONCURRENCY=4 \
    BENCH_ATTEMPTS=1 BENCH_STALE_FILE="$T/all.jsonl" \
    python bench.py 2>/dev/null | tail -1
done

echo "== continuous-mode serve load smoke (bimodal mix)"
# the ISSUE-6 engine under the straggler workload it exists for: slot
# occupancy + refills reported alongside p50/p99 (SERVE_SLO.json holds
# the enforced scheduling claim; this proves the real-model path runs)
BENCH_MODE=serve BENCH_PLATFORM=cpu BENCH_PRESET=tiny \
  BENCH_SERVE_MODE=continuous BENCH_SERVE_MIX=bimodal \
  BENCH_SERVE_REQS=8 BENCH_SERVE_CONCURRENCY=4 BENCH_ATTEMPTS=1 \
  BENCH_STALE_FILE="$T/all.jsonl" \
  python bench.py 2>/dev/null | tail -1

echo "== prefill/decode disaggregation smoke (short-heavy bimodal mix)"
# the ISSUE-11 path under the load it exists for: a NON-default
# short-request ratio (7/8 short — fingerprinted via the short_ratio
# axis) through the continuous engine, so the row carries
# prefill_total > 0 and the bucketed-prefill + length-masked slot
# machinery runs end to end on a real model (the enforced claims live
# in BYTE_BUDGET.json decode.length_axis/prefill and SERVE_SLO.json
# disaggregated, both in the suite above)
BENCH_MODE=serve BENCH_PLATFORM=cpu BENCH_PRESET=tiny \
  BENCH_SERVE_MODE=continuous BENCH_SERVE_MIX=bimodal \
  BENCH_SERVE_SHORT_RATIO=0.875 \
  BENCH_SERVE_REQS=8 BENCH_SERVE_CONCURRENCY=4 BENCH_ATTEMPTS=1 \
  BENCH_STALE_FILE="$T/all.jsonl" \
  python bench.py 2>/dev/null | tail -1

echo "== roofline (XLA cost-model floors, tiny config)"
# no --bench join here: the CPU smoke records are keyed/configured
# differently from the sweep rows, so a measured join could never match
python scripts/roofline.py --configs train_tiny --bench /nonexistent

echo "== decode-bytes smoke (backpointer beam-search byte accounting)"
# the ISSUE-7 decode byte diet's cost path end to end: compiles the
# restructured search at tiny scale and prints bytes/token + peak temp
# (the committed gate-scale claims live in BYTE_BUDGET.json's decode
# section, enforced by tests/test_bytes_gate.py in the suite above)
python scripts/roofline.py --configs decode_bytes_tiny --bench /nonexistent

echo "repro OK"
