"""Serve smoke: CollectionSource -> ServingServer -> CollectionSink on
the 8 synthetic rows (TensorFlowTest.createArticleData shape), tiny
model, CPU — the no-hardware proof that the concurrent serving path
(queue admission, micro-batching, bucket padding, future resolution,
sink fan-in) works end to end.  The continuous pass runs the
DISAGGREGATED path (ISSUE 11): mixed-length articles route through the
bucketed prefill stage into length-masked slots, with row-for-row
parity asserted against the single-stage micro-batch pass and the
prefill telemetry checked (every request prefilled, short articles at
sub-max buckets).  Wired into scripts/repro.sh.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import tempfile  # noqa: E402

from textsummarization_on_flink_tpu import obs  # noqa: E402
from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.vocab import Vocab  # noqa: E402
from textsummarization_on_flink_tpu.pipeline.io import (  # noqa: E402
    CollectionSink,
    CollectionSource,
)
from textsummarization_on_flink_tpu.serve.server import (  # noqa: E402
    ServingServer,
)
from textsummarization_on_flink_tpu.train import trainer  # noqa: E402


def main() -> None:
    # mixed LENGTHS on purpose (ISSUE 11): even rows are short (3-word)
    # articles that bucket at 8, odd rows pad out toward the top bucket
    # — the continuous pass must route them to different prefill shapes
    # while staying row-identical with the micro-batch pass
    rows = [(f"uuid-{i}",
             f"article {i} ." if i % 2 == 0
             else f"article {i} " + ". article " * 5 + ".",
             "", f"reference {i} .")
            for i in range(8)]
    vocab = Vocab(words=["article", "reference", ".", "0", "1", "2", "3",
                         "4", "5", "6", "7"])
    hps = HParams(mode="decode", batch_size=2, hidden_dim=16, emb_dim=8,
                  vocab_size=vocab.size(), max_enc_steps=16, max_dec_steps=6,
                  beam_size=2, min_dec_steps=1, max_oov_buckets=4,
                  serve_max_wait_ms=50.0, serve_max_queue=32,
                  serve_buckets="8,16")
    params = trainer.init_train_state(hps, vocab.size(), seed=0).params

    # micro-batch mode (the ISSUE-4 baseline)
    server = ServingServer(hps, vocab, params=params,
                           decode_root=tempfile.mkdtemp(prefix="serve_smoke_"))
    sink = CollectionSink()
    with server:
        server.serve(CollectionSource(rows), sink)
    assert len(sink.rows) == 8, sink.rows
    assert {r[0] for r in sink.rows} == {f"uuid-{i}" for i in range(8)}
    fill = obs.registry().histogram("serve/batch_fill")
    p50 = obs.registry().histogram("serve/e2e_latency_seconds").percentile(0.5)
    print(f"serve smoke OK: 8 rows over {fill.count} micro-batch(es), "
          f"mean fill {fill.mean:.1f}, e2e p50 {p50 * 1000:.1f} ms")

    # continuous mode (ISSUE 6): same rows through the slotted engine;
    # summaries must match the micro-batch pass row for row
    hps_c = hps.replace(serve_mode="continuous", serve_slots=2,
                        serve_refill_chunk=2)
    server_c = ServingServer(
        hps_c, vocab, params=params,
        decode_root=tempfile.mkdtemp(prefix="serve_smoke_cont_"))
    sink_c = CollectionSink()
    with server_c:
        server_c.serve(CollectionSource(rows), sink_c)
    assert len(sink_c.rows) == 8, sink_c.rows
    by_uuid = {r[0]: r for r in sink.rows}
    by_uuid_c = {r[0]: r for r in sink_c.rows}
    assert by_uuid == by_uuid_c, "continuous/micro-batch row drift"
    reg = obs.registry()
    occ = reg.histogram("serve/slot_occupancy")
    # prefill/decode disaggregation evidence (ISSUE 11): every request
    # went through the bucketed prefill stage, and the short rows
    # really ran their encoder pass at the SUB-MAX bucket (a bucket
    # histogram pinned at max_enc_steps would mean the stage pads
    # everything to full width again)
    prefills = int(reg.counter("serve/prefill_total").value)
    bucket_h = reg.histogram("serve/prefill_bucket_len")
    assert prefills == 8, f"expected 8 prefills, saw {prefills}"
    assert bucket_h.count == 8
    assert bucket_h.mean < hps.max_enc_steps, (
        f"mean prefill bucket {bucket_h.mean:.1f} pinned at "
        f"max_enc_steps={hps.max_enc_steps}: short articles are not "
        f"routing to short encoder shapes")
    print(f"continuous smoke OK: 8 rows over {occ.count} chunk step(s), "
          f"mean occupancy {occ.mean:.2f}, "
          f"refills {int(reg.counter('serve/slot_refills_total').value)}, "
          f"prefills {prefills} (mean bucket {bucket_h.mean:.1f} of "
          f"{hps.max_enc_steps}), rows identical to micro-batch "
          f"(disaggregated prefill/decode path)")


if __name__ == "__main__":
    main()
