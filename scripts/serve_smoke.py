"""Serve smoke: CollectionSource -> ServingServer -> CollectionSink on
the 8 synthetic rows (TensorFlowTest.createArticleData shape), tiny
model, CPU — the no-hardware proof that the concurrent serving path
(queue admission, micro-batching, bucket padding, future resolution,
sink fan-in) works end to end.  Wired into scripts/repro.sh.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import tempfile  # noqa: E402

from textsummarization_on_flink_tpu import obs  # noqa: E402
from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.vocab import Vocab  # noqa: E402
from textsummarization_on_flink_tpu.pipeline.io import (  # noqa: E402
    CollectionSink,
    CollectionSource,
)
from textsummarization_on_flink_tpu.serve.server import (  # noqa: E402
    ServingServer,
)
from textsummarization_on_flink_tpu.train import trainer  # noqa: E402


def main() -> None:
    rows = [(f"uuid-{i}", f"article {i} .", "", f"reference {i} .")
            for i in range(8)]
    vocab = Vocab(words=["article", "reference", ".", "0", "1", "2", "3",
                         "4", "5", "6", "7"])
    hps = HParams(mode="decode", batch_size=2, hidden_dim=16, emb_dim=8,
                  vocab_size=vocab.size(), max_enc_steps=16, max_dec_steps=6,
                  beam_size=2, min_dec_steps=1, max_oov_buckets=4,
                  serve_max_wait_ms=50.0, serve_max_queue=32)
    params = trainer.init_train_state(hps, vocab.size(), seed=0).params

    # micro-batch mode (the ISSUE-4 baseline)
    server = ServingServer(hps, vocab, params=params,
                           decode_root=tempfile.mkdtemp(prefix="serve_smoke_"))
    sink = CollectionSink()
    with server:
        server.serve(CollectionSource(rows), sink)
    assert len(sink.rows) == 8, sink.rows
    assert {r[0] for r in sink.rows} == {f"uuid-{i}" for i in range(8)}
    fill = obs.registry().histogram("serve/batch_fill")
    p50 = obs.registry().histogram("serve/e2e_latency_seconds").percentile(0.5)
    print(f"serve smoke OK: 8 rows over {fill.count} micro-batch(es), "
          f"mean fill {fill.mean:.1f}, e2e p50 {p50 * 1000:.1f} ms")

    # continuous mode (ISSUE 6): same rows through the slotted engine;
    # summaries must match the micro-batch pass row for row
    hps_c = hps.replace(serve_mode="continuous", serve_slots=2,
                        serve_refill_chunk=2)
    server_c = ServingServer(
        hps_c, vocab, params=params,
        decode_root=tempfile.mkdtemp(prefix="serve_smoke_cont_"))
    sink_c = CollectionSink()
    with server_c:
        server_c.serve(CollectionSource(rows), sink_c)
    assert len(sink_c.rows) == 8, sink_c.rows
    by_uuid = {r[0]: r for r in sink.rows}
    by_uuid_c = {r[0]: r for r in sink_c.rows}
    assert by_uuid == by_uuid_c, "continuous/micro-batch row drift"
    reg = obs.registry()
    occ = reg.histogram("serve/slot_occupancy")
    print(f"continuous smoke OK: 8 rows over {occ.count} chunk step(s), "
          f"mean occupancy {occ.mean:.2f}, "
          f"refills {int(reg.counter('serve/slot_refills_total').value)}, "
          f"rows identical to micro-batch")


if __name__ == "__main__":
    main()
