"""Fleet smoke (ISSUE 13): three in-process ServingServer replicas
behind the REAL FleetRouter, threaded, on a real tiny model — kill one
replica mid-decode under load and prove the fleet contract end to end:

  * every admitted request resolves EXACTLY ONCE (no lost futures, no
    duplicates) even though a replica died holding residents and queued
    requests — the orphans requeue on survivors through the typed
    ``ReplicaKilledError`` path (``serve/requeued_total``);
  * the answers are ROW-IDENTICAL to a single-server run of the same
    requests (same params -> same summaries, whichever replica decoded
    them — failover must not change output).

The deterministic virtual-time scenarios (rolling-swap p99 ratio,
hedge win/rate gate) are committed in SERVE_SLO.json "fleet" and
enforced by tests/test_serve_slo.py; this smoke proves the THREADED
production path runs on a real model.  Wired into scripts/repro.sh.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import tempfile  # noqa: E402

from textsummarization_on_flink_tpu import obs  # noqa: E402
from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.vocab import Vocab  # noqa: E402
from textsummarization_on_flink_tpu.obs import Registry  # noqa: E402
from textsummarization_on_flink_tpu.serve.fleet import (  # noqa: E402
    FleetRouter,
)
from textsummarization_on_flink_tpu.serve.server import (  # noqa: E402
    ServingServer,
)
from textsummarization_on_flink_tpu.train import trainer  # noqa: E402


def main() -> None:
    n_rows, n_replicas = 12, 3
    rows = [(f"uuid-{i}",
             f"article {i} ." if i % 2 == 0
             else f"article {i} " + ". article " * 5 + ".",
             "", f"reference {i} .")
            for i in range(n_rows)]
    vocab = Vocab(words=["article", "reference", "."] +
                  [str(i) for i in range(n_rows)])
    hps = HParams(mode="decode", batch_size=2, hidden_dim=16, emb_dim=8,
                  vocab_size=vocab.size(), max_enc_steps=16, max_dec_steps=6,
                  beam_size=2, min_dec_steps=1, max_oov_buckets=4,
                  serve_max_queue=64, serve_buckets="8,16",
                  serve_mode="continuous", serve_slots=2,
                  serve_refill_chunk=2, serve_replicas=n_replicas)
    params = trainer.init_train_state(hps, vocab.size(), seed=0).params

    def make_server(tag, registry=None):
        return ServingServer(
            hps, vocab, params=params, registry=registry,
            decode_root=tempfile.mkdtemp(prefix=f"fleet_smoke_{tag}_"))

    # single-server baseline: the answers failover must reproduce
    baseline = {}
    with make_server("solo") as solo:
        futs = [solo.submit(a, uuid=u, reference=r)
                for u, a, _, r in rows]
        for f in futs:
            res = f.result(timeout=600)
            baseline[res.uuid] = res.as_row()
    assert len(baseline) == n_rows

    # the fleet: per-replica registries (gauge isolation), the router on
    # the process default so its counters land where we can read them
    servers = [make_server(f"r{i}", registry=Registry())
               for i in range(n_replicas)]
    router = FleetRouter(servers, hps, registry=obs.registry())
    got = {}
    with router:
        futs = [router.submit(a, uuid=u, reference=r)
                for u, a, _, r in rows]
        # kill the most-loaded replica while its work is in flight
        victim = max((h for h in router.replicas() if not h.killed),
                     key=lambda h: h.load())
        assert victim.load() > 0, "fleet drained before the kill (smoke " \
            "needs the victim mid-decode; raise n_rows)"
        router.kill_replica(victim.rid)
        for f in futs:
            got[f.uuid] = f.result(timeout=600).as_row()

    reg = obs.registry()
    kills = int(reg.counter("serve/replica_kills_total").value)
    requeued = int(reg.counter("serve/requeued_total").value)
    assert kills == 1, kills
    assert requeued >= 1, (
        "the killed replica held no admitted work — not a failover test")
    # exactly once: one resolution per admitted uuid, none lost
    assert sorted(got) == sorted(baseline), (
        sorted(set(baseline) - set(got)), sorted(set(got) - set(baseline)))
    # row parity: failover (and routing) must not change the answers
    drift = [u for u in baseline if got[u] != baseline[u]]
    assert not drift, f"fleet/single-server row drift on {drift}"
    print(f"fleet smoke OK: {n_rows} rows over {n_replicas} replicas, "
          f"replica {victim.rid} killed under load, {requeued} request(s) "
          f"requeued on survivors, every future resolved exactly once, "
          f"rows identical to the single-server run")


if __name__ == "__main__":
    main()
