"""Fleet smoke (ISSUE 13 + 17): N ServingServer replicas behind the
REAL FleetRouter on a real tiny model — kill one replica mid-decode
under load and prove the fleet contract end to end:

  * every admitted request resolves EXACTLY ONCE (no lost futures, no
    duplicates) even though a replica died holding residents and queued
    requests — the orphans requeue on survivors through the typed
    ``ReplicaKilledError`` path (``serve/requeued_total``);
  * the answers are ROW-IDENTICAL to a single-server run of the same
    requests (same params -> same summaries, whichever replica decoded
    them — failover must not change output).

Two transports, same contract:

  * ``--transport=inproc`` (default): three in-process replicas, the
    kill is ``router.kill_replica`` (ISSUE 13).
  * ``--transport=proc`` (ISSUE 17): three SUPERVISED OS CHILD
    PROCESSES (``cli.py serve-replica``) behind the same router over
    the socket transport — the kill is a REAL SIGKILL on a live pid
    mid-decode (direct, or via the armed ``serve.proc_kill`` chaos
    point when TS_FAULTS carries it — scripts/chaos.sh's sweep).  On
    top of the inproc assertions this proves: the victim RESTARTS
    under supervision and is READMITTED through the rotation breaker's
    half-open probe, and the requeued work is witnessed in the
    SURVIVING children's events.jsonl — the SIGKILLed child wrote
    nothing, so the ledger reconstructs from the supervisor's view
    alone.

The deterministic virtual-time scenarios (rolling-swap p99 ratio,
hedge win/rate gate, socket/scrape overhead ceilings) are committed in
SERVE_SLO.json and enforced by tests/test_serve_slo.py; this smoke
proves the THREADED production paths run on a real model.  Wired into
scripts/repro.sh (both transports).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import json  # noqa: E402
import tempfile  # noqa: E402
import time  # noqa: E402

from textsummarization_on_flink_tpu import obs  # noqa: E402
from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.vocab import Vocab  # noqa: E402
from textsummarization_on_flink_tpu.obs import Registry  # noqa: E402
from textsummarization_on_flink_tpu.resilience import (  # noqa: E402
    faultinject,
)
from textsummarization_on_flink_tpu.serve.fleet import (  # noqa: E402
    FleetRouter,
)
from textsummarization_on_flink_tpu.serve.server import (  # noqa: E402
    ServingServer,
)
from textsummarization_on_flink_tpu.train import trainer  # noqa: E402

N_ROWS, N_REPLICAS = 12, 3
WORDS = ["article", "reference", "."] + [str(i) for i in range(N_ROWS)]


def _rows():
    return [(f"uuid-{i}",
             f"article {i} ." if i % 2 == 0
             else f"article {i} " + ". article " * 5 + ".",
             "", f"reference {i} .")
            for i in range(N_ROWS)]


def _hps(vocab, **overrides):
    base = dict(mode="decode", batch_size=2, hidden_dim=16, emb_dim=8,
                vocab_size=vocab.size(), max_enc_steps=16, max_dec_steps=6,
                beam_size=2, min_dec_steps=1, max_oov_buckets=4,
                serve_max_queue=64, serve_buckets="8,16",
                serve_mode="continuous", serve_slots=2,
                serve_refill_chunk=2, serve_replicas=N_REPLICAS, seed=0)
    base.update(overrides)
    return HParams(**base)


def _solo_baseline(hps, vocab, params, rows):
    """Single-server run: the answers failover must reproduce."""
    baseline = {}
    solo = ServingServer(
        hps, vocab, params=params,
        decode_root=tempfile.mkdtemp(prefix="fleet_smoke_solo_"))
    with solo:
        futs = [solo.submit(a, uuid=u, reference=r)
                for u, a, _, r in rows]
        for f in futs:
            res = f.result(timeout=600)
            baseline[res.uuid] = res.as_row()
    assert len(baseline) == len(rows)
    return baseline


def run_inproc() -> None:
    rows = _rows()
    vocab = Vocab(words=WORDS)
    hps = _hps(vocab)
    params = trainer.init_train_state(hps, vocab.size(), seed=0).params
    baseline = _solo_baseline(hps, vocab, params, rows)

    # the fleet: per-replica registries (gauge isolation), the router on
    # the process default so its counters land where we can read them
    servers = [ServingServer(
        hps, vocab, params=params, registry=Registry(),
        decode_root=tempfile.mkdtemp(prefix=f"fleet_smoke_r{i}_"))
        for i in range(N_REPLICAS)]
    router = FleetRouter(servers, hps, registry=obs.registry())
    got = {}
    with router:
        futs = [router.submit(a, uuid=u, reference=r)
                for u, a, _, r in rows]
        # kill the most-loaded replica while its work is in flight
        victim = max((h for h in router.replicas() if not h.killed),
                     key=lambda h: h.load())
        assert victim.load() > 0, "fleet drained before the kill (smoke " \
            "needs the victim mid-decode; raise N_ROWS)"
        router.kill_replica(victim.rid)
        for f in futs:
            got[f.uuid] = f.result(timeout=600).as_row()

    reg = obs.registry()
    kills = int(reg.counter("serve/replica_kills_total").value)
    requeued = int(reg.counter("serve/requeued_total").value)
    assert kills == 1, kills
    assert requeued >= 1, (
        "the killed replica held no admitted work — not a failover test")
    # exactly once: one resolution per admitted uuid, none lost
    assert sorted(got) == sorted(baseline), (
        sorted(set(baseline) - set(got)), sorted(set(got) - set(baseline)))
    # row parity: failover (and routing) must not change the answers
    drift = [u for u in baseline if got[u] != baseline[u]]
    assert not drift, f"fleet/single-server row drift on {drift}"
    print(f"fleet smoke OK: {N_ROWS} rows over {N_REPLICAS} replicas, "
          f"replica {victim.rid} killed under load, {requeued} request(s) "
          f"requeued on survivors, every future resolved exactly once, "
          f"rows identical to the single-server run")


def _finished_uuids(events_path):
    """The uuids with a ``finish`` lifecycle record in one replica's
    events.jsonl (missing/partial files yield what they hold)."""
    done = set()
    try:
        with open(events_path, "r", encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if (rec.get("kind") == "request"
                        and rec.get("event") == "finish"):
                    done.add(rec.get("uuid"))
    except OSError:
        pass
    return done


def run_proc() -> None:
    from textsummarization_on_flink_tpu.serve.procfleet import ProcFleet

    rows = _rows()
    vocab = Vocab(words=WORDS)
    workdir = tempfile.mkdtemp(prefix="fleet_smoke_proc_")
    # the children rebuild the IDENTICAL vocab from this file (same
    # word order -> same ids) and the IDENTICAL params from seed 0
    vocab_path = os.path.join(workdir, "vocab")
    with open(vocab_path, "w", encoding="utf-8") as f:
        for w in WORDS:
            f.write(f"{w} 1\n")
    hps = _hps(vocab, vocab_path=vocab_path, log_root=workdir,
               exp_name="smoke")
    params = trainer.init_train_state(hps, vocab.size(), seed=0).params
    baseline = _solo_baseline(hps, vocab, params, rows)

    reg = obs.registry()
    chaos = faultinject.plan().armed("serve.proc_kill")
    fleet = ProcFleet(hps, registry=reg, state_dir=workdir,
                      ready_timeout=300.0, replica_reset_secs=0.5,
                      restart_max_delay=0.5)
    got = {}
    fleet.start()
    assert fleet.wait_ready(timeout=300.0), (
        "process fleet failed to become ready: "
        f"{[(p.rid, p.state) for p in fleet.procs]}")
    incarnations = {p.rid: p.incarnation for p in fleet.procs}
    try:
        futs = [fleet.router.submit(a, uuid=u, reference=r)
                for u, a, _, r in rows]
        if chaos:
            # armed serve.proc_kill: the supervision thread SIGKILLs
            # the most-loaded live child once load exists; wait for it
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if any(p.deaths for p in fleet.procs):
                    break
                time.sleep(0.02)
            dead = [p for p in fleet.procs if p.deaths]
            assert dead, "serve.proc_kill armed but no child died"
            victim = dead[0]
        else:
            victim = max(fleet.procs,
                         key=lambda p: fleet.remotes[
                             fleet.procs.index(p)].load())
            vload = fleet.remotes[fleet.procs.index(victim)].load()
            assert vload > 0, "fleet drained before the kill (smoke " \
                "needs the victim mid-decode; raise N_ROWS)"
            assert victim.kill_now(), "victim child was not alive"
        for f in futs:
            got[f.uuid] = f.result(timeout=600).as_row()
        # the victim must restart under supervision and rejoin the
        # rotation through the breaker's half-open probe
        vh = next(h for h in fleet.handles if h.rid == victim.rid)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if victim.ready() and vh.in_rotation():
                break
            time.sleep(0.05)
        assert victim.incarnation > incarnations[victim.rid], (
            f"victim {victim.rid} was never restarted")
        assert victim.ready() and vh.in_rotation(), (
            f"victim {victim.rid} not readmitted: state={victim.state} "
            f"breaker={vh.breaker.state}")
    finally:
        fleet.stop(timeout=60.0)

    requeued = int(reg.counter("serve/requeued_total").value)
    deaths = sum(p.deaths for p in fleet.procs)
    assert deaths >= 1, "no child death recorded"
    assert requeued >= 1, (
        "the SIGKILLed child held no admitted work — not a failover test")
    assert sorted(got) == sorted(baseline), (
        sorted(set(baseline) - set(got)), sorted(set(got) - set(baseline)))
    drift = [u for u in baseline if got[u] != baseline[u]]
    assert not drift, f"proc-fleet/single-server row drift on {drift}"
    # the survivors' ledgers are the proof: every uuid finished in SOME
    # child's events.jsonl, and the victim's own ledger (it was
    # SIGKILLed — anything unflushed is gone) cannot account for all of
    # them, so the difference decoded on surviving replicas
    finished = {}
    for p in fleet.procs:
        finished[p.rid] = _finished_uuids(os.path.join(
            workdir, "smoke", f"replica-{p.rid}", "events.jsonl"))
    survivors_finished = set()
    for rid, done in finished.items():
        if rid != victim.rid:
            survivors_finished |= done
    assert survivors_finished, (
        "no survivor witnessed any finished request in events.jsonl")
    uncovered = set(got) - survivors_finished - finished.get(victim.rid,
                                                             set())
    assert not uncovered, (
        f"uuids resolved but witnessed by no replica ledger: {uncovered}")
    print(f"proc fleet smoke OK: {N_ROWS} rows over {N_REPLICAS} OS "
          f"processes, child {victim.rid} SIGKILLed mid-decode"
          f"{' (serve.proc_kill)' if chaos else ''}, {requeued} "
          f"request(s) requeued, victim restarted (incarnation "
          f"{victim.incarnation}) and readmitted, every future resolved "
          f"exactly once, rows identical to the single-server run, "
          f"{len(survivors_finished)} finishes witnessed by survivors")


def _locksan_gate() -> None:
    """When TS_LOCKSAN=1 armed the sanitizer, the smoke doubles as the
    runtime validation of tslint's static lock-order graph: real
    acquisitions must have been observed and NONE may have inverted
    (an inversion would already have raised the typed
    LockOrderInversionError out of the failing path)."""
    from textsummarization_on_flink_tpu.obs import locksan

    if not locksan.active():
        return
    snap = locksan.snapshot()
    assert snap["acquisitions"] > 0, (
        "TS_LOCKSAN=1 but the smoke observed no sanitized acquisitions "
        "— the serve locks are not built through obs/locksan factories")
    assert snap["inversions"] == 0, snap
    print(f"locksan OK: {snap['acquisitions']} sanitized acquisitions, "
          f"0 inversions, {len(snap['order_edges'])} order edge(s), "
          f"{snap['unmodeled_edges']} unmodeled vs "
          f"{snap['static_graph'] or 'no static graph'}")


def main() -> None:
    transport = "inproc"
    for arg in sys.argv[1:]:
        if arg.startswith("--transport="):
            transport = arg.split("=", 1)[1]
        else:
            raise SystemExit(f"unknown argument {arg!r} "
                             f"(want --transport=inproc|proc)")
    if transport == "proc":
        run_proc()
    elif transport == "inproc":
        run_inproc()
    else:
        raise SystemExit(f"unknown transport {transport!r}")
    _locksan_gate()


if __name__ == "__main__":
    main()
