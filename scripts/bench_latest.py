#!/usr/bin/env python
"""Summarize BENCH_ALL.jsonl: the newest record per run tag.

The sweep file is append-only (scripts/bench_all.sh) so one sweep row
can appear many times across reruns; BASELINE.md wants the latest view.

    python scripts/bench_latest.py [BENCH_ALL.jsonl] [--json]

Default output is a small aligned table; --json emits one JSON line per
tag (newest record verbatim) for machine use.
"""

import json
import sys


def latest_by_tag(path):
    latest = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            tag = rec.get("run") or rec.get("metric", "?")
            latest[tag] = rec  # file order == capture order: last wins
    return latest


def main(argv):
    args = [a for a in argv if not a.startswith("--")]
    path = args[0] if args else "BENCH_ALL.jsonl"
    latest = latest_by_tag(path)
    if "--json" in argv:
        for tag in latest:
            print(json.dumps(latest[tag]))
        return 0
    width = max((len(t) for t in latest), default=3)
    for tag, rec in latest.items():
        if "error" in rec:
            detail = f"ERROR: {rec['error'][:70]}"
        else:
            detail = f"{rec.get('value')} {rec.get('unit', '')}"
            if rec.get("mfu") is not None:
                detail += f"  mfu={rec['mfu']}"
            if rec.get("captured_at"):
                detail += f"  @{rec['captured_at']}"
        print(f"{tag:<{width}}  {detail}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
