#!/usr/bin/env python
"""Summarize BENCH_ALL.jsonl: the newest record per run tag.

The sweep file is append-only (scripts/bench_all.sh) so one sweep row
can appear many times across reruns; BASELINE.md wants the latest view.

    python scripts/bench_latest.py [BENCH_ALL.jsonl] [--json]

Default output is a small aligned table; --json emits one JSON line per
tag (newest record verbatim) for machine use.
"""

import json
import sys


def latest_by_tag(path):
    latest = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            tag = rec.get("run") or rec.get("metric", "?")
            # newest captured_at wins (ISO-8601 UTC sorts lexically);
            # interleaved appends from concurrent/interrupted sweeps can
            # put older records later in the file, so position alone is
            # not trustworthy.  A stale re-emission copies its source's
            # captured_at, so on timestamp ties a live record beats a
            # stale one; full ties (and stamp-less legacy lines, tying
            # at "") fall back to file order.
            old = latest.get(tag)
            if old is None or _recency(rec) >= _recency(old):
                latest[tag] = rec
    return latest


def _recency(rec):
    return (str(rec.get("captured_at", "")), 0 if rec.get("stale") else 1)


def main(argv):
    args = [a for a in argv if not a.startswith("--")]
    path = args[0] if args else "BENCH_ALL.jsonl"
    latest = latest_by_tag(path)
    if "--json" in argv:
        for tag in latest:
            print(json.dumps(latest[tag]))
        return 0
    width = max((len(t) for t in latest), default=3)
    for tag, rec in latest.items():
        if "error" in rec:
            detail = f"ERROR: {rec['error'][:70]}"
        else:
            detail = f"{rec.get('value')} {rec.get('unit', '')}"
            if rec.get("mfu") is not None:
                detail += f"  mfu={rec['mfu']}"
            if rec.get("captured_at"):
                detail += f"  @{rec['captured_at']}"
        print(f"{tag:<{width}}  {detail}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
