#!/usr/bin/env python
"""Summarize BENCH_ALL.jsonl: the newest record per run tag.

The sweep file is append-only (scripts/bench_all.sh) so one sweep row
can appear many times across reruns; BASELINE.md wants the latest view.

    python scripts/bench_latest.py [BENCH_ALL.jsonl] [--json|--md|--ratios]

Default output is a small aligned table; --json emits one JSON line per
tag (newest record verbatim) for machine use; --md emits the markdown
measured table BASELINE.md embeds (so a fresh sweep is publishable by
paste); --ratios computes each A/B lever row against its denominator
(the numbers PERF.md's predicted-band verdicts are filled from) and
always prints the capture-time gap between the two — the operator's
datum for the same-window rule pair_denominator enforces.  A heuristic
flag marks pairs whose gap makes different tunnel windows likely; its
ABSENCE is not proof of a same-window pair (windows have been observed
as short as ~2 min), the gap itself is the judgment call.
"""

import datetime
import json
import sys

# lever row -> the denominator its PERF.md band is stated against
# (scripts/bench_all.sh groups these into pair_denominator sections)
RATIO_DENOMS = {
    "decode_b1": "decode_b4",
    "decode_chunked": "decode_b4",
    "decode_while": "decode_b4",
    "decode_transformer": "decode_b4",
    "train_b16_unroll1": "train_b16",
    "train_b16_unroll16": "train_b16",
    "train_b16_pallas": "train_b16",
    "train_b16_remat": "train_b16",
    "train_b64": "train_b16",
    "train_scaled": "train_b16",
    "train_transformer_flash": "train_transformer",
    "trainer_e2e": "train_b16",
    "trainer_e2e_spd1": "train_b16",  # PERF.md states its band vs train_b16
}

# heuristic only: a sweep section banks its rows plus the paired
# denominator within a few minutes, so a bigger gap makes different
# tunnel windows LIKELY (shorter same-window gaps still exist — the
# printed gap, not the flag, is authoritative)
PAIR_WARN_SECONDS = 10 * 60


def latest_by_tag(path):
    latest = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            tag = rec.get("run") or rec.get("metric", "?")
            # newest captured_at wins (ISO-8601 UTC sorts lexically);
            # interleaved appends from concurrent/interrupted sweeps can
            # put older records later in the file, so position alone is
            # not trustworthy.  A stale re-emission copies its source's
            # captured_at, so on timestamp ties a live record beats a
            # stale one; full ties (and stamp-less legacy lines, tying
            # at "") fall back to file order.
            old = latest.get(tag)
            if old is None or _recency(rec) >= _recency(old):
                latest[tag] = rec
    return latest


def _recency(rec):
    return (str(rec.get("captured_at", "")), 0 if rec.get("stale") else 1)


def _md_cell(text):
    """Raw record strings can hold '|' (plausible in error text) or
    newlines, either of which breaks the table layout (ADVICE r4)."""
    return " ".join(str(text).split()).replace("|", "\\|")


def _md_table(latest):
    """Markdown rows (newest per tag) in sweep-file order."""
    lines = ["| Sweep row | Value | Detail | Captured | Status |",
             "|---|---|---|---|---|"]
    for tag, rec in latest.items():
        if "error" in rec:
            lines.append(f"| `{tag}` | — | {_md_cell(rec['error'][:60])} "
                         f"| — | error |")
            continue
        value = _md_cell(f"**{rec.get('value')}** {rec.get('unit', '')}")
        extras = []
        for key, label in (("step_time_ms", "step"), ("mfu", "MFU"),
                           ("p99_ms", "p99"),
                           ("p50_rtt_corrected_ms", "p50 device"),
                           ("tokens_per_sec", "tok/s"),
                           ("gen_steps_p50", "gen steps p50"),
                           ("vs_baseline", "vs K40m")):
            if rec.get(key) is not None:
                if key == "mfu":  # docs quote percent, not raw fraction
                    extras.append(f"MFU {rec[key] * 100:.1f}%")
                    continue
                suffix = (" ms" if key in ("step_time_ms", "p99_ms",
                                           "p50_rtt_corrected_ms") else "")
                extras.append(f"{label} {_md_cell(rec[key])}{suffix}")
        captured = (rec.get("captured_at") or "?").replace("T", " ")[:16]
        status = "stale" if rec.get("stale") else "live"
        lines.append(f"| `{tag}` | {value} | {', '.join(extras) or '—'} "
                     f"| {captured} | {status} |")
    return "\n".join(lines)


def _parse_ts(rec):
    try:
        return datetime.datetime.strptime(
            rec.get("captured_at", ""), "%Y-%m-%dT%H:%M:%SZ")
    except ValueError:
        return None


def _ratio_rows(latest):
    """[(tag, denom, ratio, unit, pair_gap_s|None, flags)] for every
    lever row whose numerator AND denominator are banked live."""
    rows = []
    for tag, denom in RATIO_DENOMS.items():
        num, den = latest.get(tag), latest.get(denom)
        if not num or not den:
            continue
        if any("error" in r or r.get("stale") for r in (num, den)):
            continue
        if not den.get("value"):
            continue
        ratio = num["value"] / den["value"]
        t_num, t_den = _parse_ts(num), _parse_ts(den)
        gap = (abs((t_num - t_den).total_seconds())
               if t_num and t_den else None)
        flags = []
        if gap is None:
            flags.append("UNDATED")
        elif gap > PAIR_WARN_SECONDS:
            flags.append("LIKELY CROSS-WINDOW")  # re-pair before verdicts
        rows.append((tag, denom, ratio, num.get("unit", ""), gap, flags))
    return rows


def _print_ratios(latest):
    rows = _ratio_rows(latest)
    if not rows:
        print("no live lever/denominator pairs banked yet")
        return
    width = max(len(t) for t, *_ in rows)
    for tag, denom, ratio, unit, gap, flags in rows:
        gap_s = "gap ?" if gap is None else f"gap {gap / 60:.1f} min"
        note = ("  [" + ", ".join(flags) + "]") if flags else ""
        print(f"{tag:<{width}}  {ratio:6.3f}x vs {denom} "
              f"({latest[tag]['value']} / {latest[denom]['value']} {unit}; "
              f"{gap_s}){note}")


def main(argv):
    args = [a for a in argv if not a.startswith("--")]
    path = args[0] if args else "BENCH_ALL.jsonl"
    latest = latest_by_tag(path)
    if "--json" in argv:
        for tag in latest:
            print(json.dumps(latest[tag]))
        return 0
    if "--md" in argv:
        print(_md_table(latest))
        return 0
    if "--ratios" in argv:
        _print_ratios(latest)
        return 0
    width = max((len(t) for t in latest), default=3)
    for tag, rec in latest.items():
        if "error" in rec:
            detail = f"ERROR: {rec['error'][:70]}"
        else:
            detail = f"{rec.get('value')} {rec.get('unit', '')}"
            if rec.get("mfu") is not None:
                detail += f"  mfu={rec['mfu']}"
            if rec.get("captured_at"):
                detail += f"  @{rec['captured_at']}"
        print(f"{tag:<{width}}  {detail}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
