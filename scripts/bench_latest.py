#!/usr/bin/env python
"""Summarize BENCH_ALL.jsonl: the newest record per run tag.

The sweep file is append-only (scripts/bench_all.sh) so one sweep row
can appear many times across reruns; BASELINE.md wants the latest view.

    python scripts/bench_latest.py [BENCH_ALL.jsonl] [--json|--md]

Default output is a small aligned table; --json emits one JSON line per
tag (newest record verbatim) for machine use; --md emits the markdown
measured table BASELINE.md embeds (so a fresh sweep is publishable by
paste).
"""

import json
import sys


def latest_by_tag(path):
    latest = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            tag = rec.get("run") or rec.get("metric", "?")
            # newest captured_at wins (ISO-8601 UTC sorts lexically);
            # interleaved appends from concurrent/interrupted sweeps can
            # put older records later in the file, so position alone is
            # not trustworthy.  A stale re-emission copies its source's
            # captured_at, so on timestamp ties a live record beats a
            # stale one; full ties (and stamp-less legacy lines, tying
            # at "") fall back to file order.
            old = latest.get(tag)
            if old is None or _recency(rec) >= _recency(old):
                latest[tag] = rec
    return latest


def _recency(rec):
    return (str(rec.get("captured_at", "")), 0 if rec.get("stale") else 1)


def _md_cell(text):
    """Raw record strings can hold '|' (plausible in error text) or
    newlines, either of which breaks the table layout (ADVICE r4)."""
    return " ".join(str(text).split()).replace("|", "\\|")


def _md_table(latest):
    """Markdown rows (newest per tag) in sweep-file order."""
    lines = ["| Sweep row | Value | Detail | Captured | Status |",
             "|---|---|---|---|---|"]
    for tag, rec in latest.items():
        if "error" in rec:
            lines.append(f"| `{tag}` | — | {_md_cell(rec['error'][:60])} "
                         f"| — | error |")
            continue
        value = _md_cell(f"**{rec.get('value')}** {rec.get('unit', '')}")
        extras = []
        for key, label in (("step_time_ms", "step"), ("mfu", "MFU"),
                           ("p99_ms", "p99"),
                           ("p50_rtt_corrected_ms", "p50 device"),
                           ("tokens_per_sec", "tok/s"),
                           ("gen_steps_p50", "gen steps p50"),
                           ("vs_baseline", "vs K40m")):
            if rec.get(key) is not None:
                if key == "mfu":  # docs quote percent, not raw fraction
                    extras.append(f"MFU {rec[key] * 100:.1f}%")
                    continue
                suffix = (" ms" if key in ("step_time_ms", "p99_ms",
                                           "p50_rtt_corrected_ms") else "")
                extras.append(f"{label} {_md_cell(rec[key])}{suffix}")
        captured = (rec.get("captured_at") or "?").replace("T", " ")[:16]
        status = "stale" if rec.get("stale") else "live"
        lines.append(f"| `{tag}` | {value} | {', '.join(extras) or '—'} "
                     f"| {captured} | {status} |")
    return "\n".join(lines)


def main(argv):
    args = [a for a in argv if not a.startswith("--")]
    path = args[0] if args else "BENCH_ALL.jsonl"
    latest = latest_by_tag(path)
    if "--json" in argv:
        for tag in latest:
            print(json.dumps(latest[tag]))
        return 0
    if "--md" in argv:
        print(_md_table(latest))
        return 0
    width = max((len(t) for t in latest), default=3)
    for tag, rec in latest.items():
        if "error" in rec:
            detail = f"ERROR: {rec['error'][:70]}"
        else:
            detail = f"{rec.get('value')} {rec.get('unit', '')}"
            if rec.get("mfu") is not None:
                detail += f"  mfu={rec['mfu']}"
            if rec.get("captured_at"):
                detail += f"  @{rec['captured_at']}"
        print(f"{tag:<{width}}  {detail}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
