#!/usr/bin/env bash
# Chaos harness (RESILIENCE.md): drive every recovery path under
# deterministic fault injection.
#
#   scripts/chaos.sh          # chaos/resilience/bridge suites + TS_FAULTS sweeps
#
# Two layers:
#   1. the pytest chaos suite — each test pins its own fault plan
#      (seeded, via HParams(faults=...) or faultinject.use_plan), so the
#      exact same call indices fail on every run;
#   2. TS_FAULTS sweeps — the PROCESS-WIDE env arming path, exercised by
#      small end-to-end smokes per injection point (train divergence
#      recovery, source reconnect, checkpoint fallback, etl worker
#      restarts), asserting recovery through the resilience/* counters.
set -euo pipefail
cd "$(dirname "$0")/.."

export PYTHONPATH="$PWD"
export JAX_PLATFORMS=cpu
export XLA_FLAGS="--xla_force_host_platform_device_count=8"

echo "== chaos + resilience + bridge suites (pinned per-test fault plans)"
python -m pytest tests/test_chaos.py tests/test_resilience.py \
  tests/test_bridge.py -q -p no:cacheprovider

echo
echo "== TS_FAULTS sweep: train.step_nan (divergence recovery end-to-end)"
TS_FAULTS="train.step_nan:1.0:7:3" python - <<'PY'
import numpy as np
from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.batching import Batch, SummaryExample
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.train import trainer as trainer_lib
import tempfile

hps = HParams(batch_size=2, max_enc_steps=6, max_dec_steps=5, min_dec_steps=1,
              hidden_dim=4, emb_dim=3, max_oov_buckets=2, vocab_size=0,
              nan_skip_steps=2, nan_max_rollbacks=1,
              log_root=tempfile.mkdtemp(), exp_name="chaos")
vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
exs = [SummaryExample.build("a b c d", ["b c ."], vocab, hps),
       SummaryExample.build("c d e f", ["d e ."], vocab, hps)]
batch = Batch(exs, hps, vocab)

class FixedBatcher:
    n = 30
    def next_batch(self):
        if self.n <= 0:
            return None
        self.n -= 1
        return batch

trainer = trainer_lib.Trainer(hps, vocab.size(), FixedBatcher())
state = trainer.train(num_steps=6)
assert int(np.asarray(state.step)) == 6, "training did not complete"
skips = obs.counter("resilience/train/nan_skips_total").value
rollbacks = obs.counter("resilience/train/rollbacks_total").value
assert (skips, rollbacks) == (2, 1), (skips, rollbacks)
print(f"train.step_nan OK: {int(skips)} skips, {int(rollbacks)} rollback, "
      f"resumed to step 6 with no manual intervention")
PY

echo
echo "== TS_FAULTS sweep: io.read (source reconnect, exactly-once)"
TS_FAULTS="io.read:1.0:0:2" python - <<'PY'
import socketserver, threading
from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.pipeline import io as io_lib
from textsummarization_on_flink_tpu.resilience import faultinject

lines = [io_lib.Message(f"u{i}", f"art {i}", "", "r").to_json()
         for i in range(5)]

class H(socketserver.StreamRequestHandler):
    def handle(self):
        try:
            for line in lines:
                self.wfile.write((line + "\n").encode())
        except (BrokenPipeError, ConnectionResetError):
            pass

srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), H)
srv.daemon_threads = True
threading.Thread(target=srv.serve_forever, daemon=True).start()
src = io_lib.ResilientSource(
    lambda: io_lib.SocketSource("127.0.0.1", srv.server_address[1],
                                max_count=5),
    max_reconnects=4, seed=0, sleep=lambda d: None)
rows = list(src.rows())
srv.shutdown(); srv.server_close()
assert [r[0] for r in rows] == [f"u{i}" for i in range(5)], rows
fires = faultinject.plan().stats()["io.read"]["fires"]
reconnects = obs.counter("resilience/io_reconnects_total").value
assert fires == 2 and reconnects == 2, (fires, reconnects)
print(f"io.read OK: {fires} injected faults, {int(reconnects)} reconnects, "
      f"5 rows delivered exactly once")
PY

echo
echo "== TS_FAULTS sweep: ckpt.load (corruption fallback chain)"
TS_FAULTS="ckpt.load:1.0:0:1" python - <<'PY'
import tempfile
import numpy as np
from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.checkpoint import checkpointer as ckpt_lib
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.train import trainer as trainer_lib

hps = HParams(batch_size=2, max_enc_steps=6, max_dec_steps=5, min_dec_steps=1,
              hidden_dim=4, emb_dim=3, max_oov_buckets=2, vocab_size=0)
d = tempfile.mkdtemp()
ck = ckpt_lib.Checkpointer(d, hps=hps)
s1 = trainer_lib.init_train_state(hps, vsize=12, seed=0)
ck.save(s1)
ck.save(s1._replace(step=s1.step + 5))
restored = ck.restore()  # newest load fails (injected) -> next-older serves
assert restored is not None
assert int(np.asarray(restored.step)) == int(np.asarray(s1.step))
fallbacks = obs.counter("resilience/ckpt_fallbacks_total").value
assert fallbacks == 1, fallbacks
print("ckpt.load OK: corrupt-latest fell back to the next-older checkpoint")
PY

echo
echo "== TS_FAULTS sweep: etl.worker (bounded restart budget)"
TS_FAULTS="etl.worker:1.0:0:2" python - <<'PY'
from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.batcher import Batcher
from textsummarization_on_flink_tpu.data.vocab import Vocab

hps = HParams(batch_size=2, max_enc_steps=6, max_dec_steps=5, min_dec_steps=1,
              hidden_dim=4, emb_dim=3, max_oov_buckets=2, vocab_size=0)
vocab = Vocab(words=["the", "cat", "sat", "on", "mat", "."])
b = Batcher("", vocab, hps, single_pass=True,
            example_source=lambda: iter(
                [("the cat sat", "<s> the cat . </s>")] * 4),
            max_worker_restarts=3)
n = 0
while b.next_batch() is not None:
    n += 1
restarts = obs.counter("resilience/etl_worker_restarts_total").value
assert n == 2 and restarts == 2, (n, restarts)
print(f"etl.worker OK: {int(restarts)} crash restarts, data still flowed")
PY

echo
echo "== TS_FAULTS sweep: serve.replica_kill (fleet failover, exactly-once)"
TS_FAULTS="serve.replica_kill:1.0:0:1" python - <<'PY'
from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode.decoder import DecodedResult
from textsummarization_on_flink_tpu.obs import Registry
from textsummarization_on_flink_tpu.resilience import faultinject
from textsummarization_on_flink_tpu.serve.fleet import FleetRouter
from textsummarization_on_flink_tpu.serve.server import ServingServer

class NullDecoder:
    def maybe_reload_checkpoint(self, last):
        return last

class SimEngine:
    """3-chunk-per-request slot engine (jax-free): enough residency for
    the injected kill to land mid-decode."""
    def __init__(self, slots=2):
        self.slots, self._rem = slots, [0] * slots
        self._act = [False] * slots
    def pack(self, idx, ex):
        self._act[idx], self._rem[idx] = True, 3
    def step(self):
        fin = []
        for i in range(self.slots):
            if self._act[i]:
                self._rem[i] -= 1
                if self._rem[i] <= 0:
                    fin.append(i)
        return fin
    def unpack(self, idx, ex):
        self._act[idx] = False
        return DecodedResult(uuid=ex.uuid, article=ex.original_article,
                             decoded_words=["ok", "."],
                             reference=ex.reference, abstract_sents=[])
    def release(self, idx):
        self._act[idx] = False

vocab = Vocab(words=["w"])
hps = HParams(mode="decode", batch_size=2, vocab_size=vocab.size(),
              max_enc_steps=8, max_dec_steps=6, beam_size=2,
              min_dec_steps=1, max_oov_buckets=4, serve_max_queue=64,
              serve_mode="continuous", serve_slots=2, serve_refill_chunk=1,
              serve_replicas=3)
servers = [ServingServer(hps, vocab, decoder=NullDecoder(),
                         engine=SimEngine(), registry=Registry())
           for _ in range(3)]
router = FleetRouter(servers, hps)  # picks up the TS_FAULTS process plan
futs = [router.submit("w w w .", uuid=f"u{i}") for i in range(12)]
rounds = 0
while not all(f.done() for f in futs):
    rounds += 1
    assert rounds < 500, "fleet did not drain"
    router.tick()  # the armed serve.replica_kill fires on the first tick
    for h in router.replicas():
        if not h.killed:
            h.server.tick_once(poll=0.0)
results = [f.result(timeout=1) for f in futs]
assert [r.uuid for r in results] == [f"u{i}" for i in range(12)]
router.stop()
reg = obs.registry()
fires = faultinject.plan().stats()["serve.replica_kill"]["fires"]
kills = int(reg.counter("serve/replica_kills_total").value)
requeued = int(reg.counter("serve/requeued_total").value)
assert fires == 1 and kills == 1, (fires, kills)
assert requeued >= 1, requeued
assert sum(h.killed for h in router.replicas()) == 1
print(f"serve.replica_kill OK: 1 injected replica death, {requeued} "
      f"request(s) requeued on survivors, 12 futures resolved exactly once")
PY

echo
echo "== TS_FAULTS sweep: serve.proc_kill (OS-process fleet, SIGKILL failover)"
# the ISSUE-17 process boundary end to end: 3 supervised child
# processes behind the socket transport; the armed point makes the
# supervision thread SIGKILL the most-loaded live pid mid-decode, and
# the smoke asserts exactly-once + row parity + typed requeues on
# survivors + the victim restarted and readmitted through the rotation
# breaker's half-open probe (full contract in scripts/fleet_smoke.py)
# TS_LOCKSAN arms the runtime lock-order sanitizer on the sweep: the
# kill/requeue path is the richest lock interleaving the repo has, so
# it doubles as the inversion gate (obs/locksan; zero inversions)
TS_LOCKSAN=1 TS_FAULTS="serve.proc_kill:1.0:0:1" python scripts/fleet_smoke.py \
  --transport=proc

echo
echo "== TS_FAULTS sweep: serve.cache_fault (front door degrades to miss)"
TS_FAULTS="serve.cache_fault:1.0:0" python - <<'PY'
from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode.decoder import DecodedResult
from textsummarization_on_flink_tpu.resilience import faultinject
from textsummarization_on_flink_tpu.serve.server import ServingServer

class EchoDecoder:
    """Content-deterministic stub: the cache CONTRACT (never a wrong
    summary, never a hung future) is host-side, no device needed."""
    def should_degrade(self, deadline):
        return False
    def decode_batch(self, batch, deadline=None, tier=None):
        return [DecodedResult(
                    uuid=batch.uuids[b], article=batch.original_articles[b],
                    decoded_words=batch.original_articles[b].split()[:3],
                    reference=batch.references[b], abstract_sents=[])
                for b in range(len(batch.uuids)) if batch.real_mask[b]]
    def maybe_reload_checkpoint(self, last):
        return last

vocab = Vocab(words=["the", "cat", "sat", "."])
hps = HParams(mode="decode", batch_size=2, vocab_size=vocab.size(),
              max_enc_steps=8, max_dec_steps=4, beam_size=2,
              min_dec_steps=1, max_oov_buckets=4, serve_max_queue=16,
              serve_cache_entries=8)
with ServingServer(hps, vocab, decoder=EchoDecoder()) as server:
    r1 = server.submit("the cat sat .", uuid="u1").result(timeout=30)
    r2 = server.submit("the cat sat .", uuid="u2").result(timeout=30)
reg = obs.registry()
fires = faultinject.plan().stats()["serve.cache_fault"]["fires"]
hits = int(reg.counter("serve/cache_hits_total").value)
errors = int(reg.counter("serve/cache_errors_total").value)
decodes = int(reg.counter("serve/completed_total").value)
assert r1.summary == r2.summary, (r1.summary, r2.summary)
assert hits == 0 and decodes == 2, (hits, decodes)
assert fires >= 2 and errors >= 2, (fires, errors)
print(f"serve.cache_fault OK: {fires} injected cache faults degraded to "
      f"miss-and-decode ({decodes} decodes, 0 hits), summaries identical, "
      f"every future resolved")
PY

echo
echo "== TS_FAULTS sweep: serve.arena_full (paged admission requeues, never rejects)"
TS_FAULTS="serve.arena_full:1.0:0:2" python - <<'PY'
import glob
import tempfile
from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode.decoder import DecodedResult
from textsummarization_on_flink_tpu.obs import flightrec
from textsummarization_on_flink_tpu.resilience import faultinject
from textsummarization_on_flink_tpu.serve.server import ServingServer

class NullDecoder:
    def maybe_reload_checkpoint(self, last):
        return last

class PagedSimEngine:
    """Jax-free paged slot engine (ISSUE 20): a 4-page arena over 2
    slots, 2 decode chunks per request — the REAL ContinuousBatcher
    does the page-gated admission; the armed serve.arena_full point
    lands the allocation failure inside pack."""
    paged = True
    def __init__(self, slots=2, pages=4, page_words=4):
        self.slots, self._cap = slots, pages
        self._free = list(range(pages))
        self._page_words = page_words
        self._held = [[] for _ in range(slots)]
        self._rem = [0] * slots
    def prefill(self, ex):
        return ex
    def pages_needed(self, ex):
        words = len(ex.original_article.split())
        return max(1, -(-words // self._page_words))
    def free_pages(self):
        return len(self._free)
    def arena_stats(self):
        in_use = self._cap - len(self._free)
        return {"capacity": self._cap, "free": len(self._free),
                "in_use": in_use, "fill": in_use / self._cap}
    def pack(self, idx, ex):
        self._held[idx] = [self._free.pop()
                           for _ in range(self.pages_needed(ex))]
        self._rem[idx] = 2
    def step(self):
        fin = []
        for i in range(self.slots):
            if self._rem[i] > 0:
                self._rem[i] -= 1
                if self._rem[i] == 0:
                    fin.append(i)
        return fin
    def _release_pages(self, idx):
        self._free.extend(self._held[idx])
        self._held[idx] = []
    def unpack(self, idx, ex):
        self._release_pages(idx)
        return DecodedResult(uuid=ex.uuid, article=ex.original_article,
                             decoded_words=["ok", "."],
                             reference=ex.reference, abstract_sents=[])
    def release(self, idx):
        self._release_pages(idx)
        self._rem[idx] = 0

vocab = Vocab(words=["w"])
hps = HParams(mode="decode", batch_size=2, vocab_size=vocab.size(),
              max_enc_steps=8, max_dec_steps=6, beam_size=2,
              min_dec_steps=1, max_oov_buckets=4, serve_max_queue=64,
              serve_mode="continuous", serve_slots=2, serve_refill_chunk=1)
ring = tempfile.mkdtemp()
reg = obs.registry()
flightrec.install_flight_recorder(reg, ring)
engine = PagedSimEngine()
with ServingServer(hps, vocab, decoder=NullDecoder(),
                   engine=engine) as server:
    futs = [server.submit("w w w w w w .", uuid=f"u{i}") for i in range(8)]
    results = [f.result(timeout=60) for f in futs]
assert [r.uuid for r in results] == [f"u{i}" for i in range(8)]
fires = faultinject.plan().stats()["serve.arena_full"]["fires"]
fails = int(reg.counter("serve/arena_alloc_failures_total").value)
assert fires == 2 and fails >= 2, (fires, fails)
assert engine.arena_stats()["in_use"] == 0, engine.arena_stats()
dumps = glob.glob(ring + "/flight_arena_exhausted*.jsonl")
assert len(dumps) == 1, dumps  # rising edge only: ONE dump per episode
print(f"serve.arena_full OK: {fires} injected allocation failures "
      f"requeued (never rejected), 8 futures resolved exactly once, "
      f"arena drained to 0, 1 flight dump ({dumps[0].rsplit('/', 1)[-1]})")
PY

echo
echo "chaos OK"
