#!/usr/bin/env bash
# Tunnel-window extras, run by bench_when_up.sh AFTER the sweep is
# complete and its rows are committed (never before — banked numbers
# outrank diagnostics).  Two captures, both idempotent (skipped once
# their output exists), both pure capture — the analysis/BASELINE.md
# write-up happens offline where no tunnel window is being spent:
#
#  1. exp/roofline_tpu.json — XLA cost_analysis of the real train step
#     compiled ON THE TPU BACKEND, with per-phase attribution.  The
#     roofline/attribution story so far rests on CPU-compiled HLO byte
#     estimates that the one measured row already proved ~10% optimistic
#     (13.37 ms measured vs 14.8 ms CPU-HLO "floor" — TPU fusion decides
#     the real byte traffic, VERDICT r4 weak #4).
#  2. exp/trace_r05/ — a TS_PROFILE_DIR profiler trace captured through
#     a short end-to-end Trainer run (BENCH_MODE=trainer drives the real
#     Trainer, which starts/stops jax.profiler at dispatch boundaries,
#     train/trainer.py:482-528) for op-level arbitration.
set -u
cd "$(dirname "$0")/.."
mkdir -p exp

if [ ! -s exp/roofline_tpu.json ]; then
  echo "[extras] TPU-compiled roofline attribution (train_b16 + train_transformer)"
  if timeout 900 python scripts/roofline.py \
      --configs train_b16,train_transformer --attribute --json \
      > exp/roofline_tpu.json.tmp 2> exp/roofline_tpu.log; then
    mv exp/roofline_tpu.json.tmp exp/roofline_tpu.json
    echo "[extras] roofline_tpu.json captured"
  else
    echo "[extras] TPU roofline failed (rc=$?) — see exp/roofline_tpu.log"
  fi
fi

if [ ! -d exp/trace_r05 ] || [ -z "$(ls -A exp/trace_r05 2>/dev/null)" ]; then
  echo "[extras] profiler trace via a short e2e trainer run"
  rm -rf exp/trace_r05.tmp
  # success = the profiler actually wrote an xplane file, NOT bench.py's
  # exit code: the supervisor exits 0 on its stale-fallback path (a
  # tunnel drop mid-trace would serve the sweep's just-banked
  # trainer_e2e row), which would bank a truncated trace forever
  if env TS_PROFILE_DIR="$PWD/exp/trace_r05.tmp" BENCH_NO_RECORD=1 \
      BENCH_STALE_FILE=/dev/null \
      BENCH_MODE=trainer BENCH_STEPS=24 BENCH_ATTEMPTS=1 \
      BENCH_TIMEOUT=600 timeout 700 python bench.py \
      > exp/trace_bench.out 2>&1 \
      && find exp/trace_r05.tmp -name "*.xplane.pb" | grep -q .; then
    mv exp/trace_r05.tmp exp/trace_r05
    echo "[extras] trace captured -> exp/trace_r05"
  else
    echo "[extras] trace capture failed — see exp/trace_bench.out"
  fi
fi
echo "[extras] done"
