#!/usr/bin/env bash
# Fast lint gate (wired into scripts/repro.sh ahead of the full suite).
#
# Uses ruff (config: ruff.toml) when the rig has it; this container
# bakes its toolchain and forbids network installs, so absent ruff the
# gate degrades to a compileall syntax sweep — it still catches the
# syntax-error class before the test tier spends minutes importing.
set -euo pipefail
cd "$(dirname "$0")/.."

if python -m ruff --version >/dev/null 2>&1; then
  exec python -m ruff check .
elif command -v ruff >/dev/null 2>&1; then
  exec ruff check .
fi

echo "[lint] ruff unavailable; running compileall syntax sweep instead"
python - <<'EOF'
import compileall
import re
import sys

ok = compileall.compile_dir(
    ".", quiet=1, rx=re.compile(r"\.git|\.jax_cache|exp/"), force=False)
sys.exit(0 if ok else 1)
EOF
