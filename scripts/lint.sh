#!/usr/bin/env bash
# Fast lint gate (wired into scripts/repro.sh ahead of the full suite).
# Two stages, split by responsibility (see ruff.toml header + ANALYSIS.md):
#
#   1. ruff E/F/W — generic syntax/pyflakes class.  The rig may lack
#      ruff (this container bakes its toolchain and forbids network
#      installs), so absent ruff the stage degrades to a compileall
#      syntax sweep — it still catches the syntax-error class before
#      the test tier spends minutes importing.
#   2. tools/tslint — the repo-native AST rules ruff cannot express
#      (TS001 jit purity, TS002 host-sync-in-hot-loop, TS003 monotonic
#      clock, TS004 lock discipline, TS005 broad-except, TS006 donation
#      aliasing).  Stdlib-only, so it always runs; grandfathered
#      findings live in tools/tslint/baseline.json.  The scan covers
#      the package AND tools/ — the analyzer passes its own rules.
#   3. tools/tslint --rules TS007..TS010 — the interprocedural
#      concurrency rules (lock-order cycles, blocking-under-lock,
#      cross-thread unlocked writes, future single-resolution) run as
#      their own stage so a concurrency regression is named as such in
#      the gate output, not buried in the per-file sweep.
set -euo pipefail
cd "$(dirname "$0")/.."

if python -m ruff --version >/dev/null 2>&1; then
  python -m ruff check .
elif command -v ruff >/dev/null 2>&1; then
  ruff check .
else
  echo "[lint] ruff unavailable; running compileall syntax sweep instead"
  python - <<'EOF'
import compileall
import re
import sys

ok = compileall.compile_dir(
    ".", quiet=1, rx=re.compile(r"\.git|\.jax_cache|exp/"), force=False)
sys.exit(0 if ok else 1)
EOF
fi

echo "[lint] tslint (repo-native AST rules, ANALYSIS.md)"
python -m tools.tslint --baseline tools/tslint/baseline.json \
  textsummarization_on_flink_tpu tools

echo "[lint] tslint concurrency rules (TS007-TS010, ANALYSIS.md)"
python -m tools.tslint --rules TS007,TS008,TS009,TS010 \
  --baseline tools/tslint/baseline.json \
  textsummarization_on_flink_tpu tools
