#!/bin/bash
# Standalone raw-text inference launcher (reference run_inference.sh parity).
python -m textsummarization_on_flink_tpu --mode=decode --inference=1 --coverage=1 "$@"
