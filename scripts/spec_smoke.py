"""Speculative-tier smoke (ISSUE 10): bootstrap an AAN draft from a
tiny transformer's own params (the `spec_draft="map"` recipe), run the
draft-then-verify fast path through the REAL decoder's tier surface,
and assert token exactness against the greedy tier — the no-hardware
proof that draft init -> spec decode -> verify works end to end.
Wired into scripts/repro.sh.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import tempfile  # noqa: E402

import jax  # noqa: E402

from textsummarization_on_flink_tpu import obs  # noqa: E402
from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.batching import (  # noqa: E402
    Batch,
    SummaryExample,
)
from textsummarization_on_flink_tpu.data.vocab import Vocab  # noqa: E402
from textsummarization_on_flink_tpu.decode.decoder import (  # noqa: E402
    BeamSearchDecoder,
)
from textsummarization_on_flink_tpu.models import get_family  # noqa: E402


def main() -> None:
    vocab = Vocab(words=["article", "reference", ".", "0", "1", "2", "3",
                         "4", "5", "6", "7"])
    hps = HParams(mode="decode", batch_size=4, hidden_dim=16, emb_dim=16,
                  vocab_size=vocab.size(), max_enc_steps=16,
                  max_dec_steps=8, beam_size=2, min_dec_steps=1,
                  max_oov_buckets=4, model_family="transformer",
                  num_heads=2, enc_layers=1, dec_layers=2,
                  spec_k=3, draft_dec_layers=1, spec_draft="map")
    hps.validate()
    params = get_family(hps.model_family).init_params(
        hps, vocab.size(), jax.random.PRNGKey(0))
    # the decoder builds the mapped draft itself (spec_draft="map")
    decoder = BeamSearchDecoder(
        hps, vocab, batcher=None, params=params,
        decode_root=tempfile.mkdtemp(prefix="spec_smoke_"))
    assert decoder.has_draft, "mapped draft bootstrap failed"

    examples = [SummaryExample.build(f"article {i} .", [], vocab, hps,
                                     uuid=f"uuid-{i}") for i in range(4)]
    batch = Batch(examples, hps, vocab)
    greedy = decoder.decode_batch(batch, tier="greedy")
    spec = decoder.decode_batch(batch, tier="spec")
    draft = decoder.decode_batch(batch, tier="draft")
    assert len(spec) == len(greedy) == len(draft) == 4
    for g, s in zip(greedy, spec):
        assert g.decoded_words == s.decoded_words, (
            f"spec tier drifted from greedy for {g.uuid}: "
            f"{g.decoded_words} vs {s.decoded_words}")
        assert s.tier == "spec"
    reg = obs.registry()
    cycles = int(reg.counter("decode/spec_cycles_total").value)
    drafted = int(reg.counter("decode/spec_draft_tokens_total").value)
    accepted = int(reg.counter("decode/spec_accepted_tokens_total").value)
    rate = accepted / drafted if drafted else 0.0
    print(f"spec smoke OK: 4 rows token-exact with greedy; "
          f"{cycles} verify cycle(s), acceptance {accepted}/{drafted} "
          f"({rate:.0%}); draft tier served {len(draft)} rows")


if __name__ == "__main__":
    main()
