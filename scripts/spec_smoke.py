"""Speculative-tier smoke (ISSUE 10): bootstrap an AAN draft from a
tiny transformer's own params (the `spec_draft="map"` recipe), run the
draft-then-verify fast path through the REAL decoder's tier surface,
and assert token exactness against the greedy tier — the no-hardware
proof that draft init -> spec decode -> verify works end to end.
Wired into scripts/repro.sh.

``--distill`` (ISSUE 12) runs the distilled-narrow-draft flow instead:
train a tiny teacher a few steps on synthetic copy data, distill a
NARROW draft (draft_hidden < H, factored vocab head) from its greedy
outputs through train/distill.DistillTrainer, then spec-decode under
the acceptance-adaptive controller and assert token exactness vs
greedy — the no-hardware proof that distill -> narrow spec ->
adaptive-k works end to end (the committed acceptance floor lives in
BYTE_BUDGET.json spec.distill, enforced by tests/test_distill.py).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import tempfile  # noqa: E402

import jax  # noqa: E402

from textsummarization_on_flink_tpu import obs  # noqa: E402
from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.batching import (  # noqa: E402
    Batch,
    SummaryExample,
)
from textsummarization_on_flink_tpu.data.vocab import Vocab  # noqa: E402
from textsummarization_on_flink_tpu.decode.decoder import (  # noqa: E402
    BeamSearchDecoder,
)
from textsummarization_on_flink_tpu.models import get_family  # noqa: E402


def main() -> None:
    vocab = Vocab(words=["article", "reference", ".", "0", "1", "2", "3",
                         "4", "5", "6", "7"])
    hps = HParams(mode="decode", batch_size=4, hidden_dim=16, emb_dim=16,
                  vocab_size=vocab.size(), max_enc_steps=16,
                  max_dec_steps=8, beam_size=2, min_dec_steps=1,
                  max_oov_buckets=4, model_family="transformer",
                  num_heads=2, enc_layers=1, dec_layers=2,
                  spec_k=3, draft_dec_layers=1, spec_draft="map")
    hps.validate()
    params = get_family(hps.model_family).init_params(
        hps, vocab.size(), jax.random.PRNGKey(0))
    # the decoder builds the mapped draft itself (spec_draft="map")
    decoder = BeamSearchDecoder(
        hps, vocab, batcher=None, params=params,
        decode_root=tempfile.mkdtemp(prefix="spec_smoke_"))
    assert decoder.has_draft, "mapped draft bootstrap failed"

    examples = [SummaryExample.build(f"article {i} .", [], vocab, hps,
                                     uuid=f"uuid-{i}") for i in range(4)]
    batch = Batch(examples, hps, vocab)
    greedy = decoder.decode_batch(batch, tier="greedy")
    spec = decoder.decode_batch(batch, tier="spec")
    draft = decoder.decode_batch(batch, tier="draft")
    assert len(spec) == len(greedy) == len(draft) == 4
    for g, s in zip(greedy, spec):
        assert g.decoded_words == s.decoded_words, (
            f"spec tier drifted from greedy for {g.uuid}: "
            f"{g.decoded_words} vs {s.decoded_words}")
        assert s.tier == "spec"
    reg = obs.registry()
    cycles = int(reg.counter("decode/spec_cycles_total").value)
    drafted = int(reg.counter("decode/spec_draft_tokens_total").value)
    accepted = int(reg.counter("decode/spec_accepted_tokens_total").value)
    rate = accepted / drafted if drafted else 0.0
    print(f"spec smoke OK: 4 rows token-exact with greedy; "
          f"{cycles} verify cycle(s), acceptance {accepted}/{drafted} "
          f"({rate:.0%}); draft tier served {len(draft)} rows")


def distill_main() -> None:
    """The ISSUE-12 smoke: synthetic distillation of the narrow draft,
    then adaptive spec decode, token-exact with greedy."""
    import numpy as np  # noqa: E402

    from textsummarization_on_flink_tpu.config import (  # noqa: E402
        derive_draft_hps,
    )
    from textsummarization_on_flink_tpu.decode import (  # noqa: E402
        beam_search,
        speculative,
    )
    from textsummarization_on_flink_tpu.models import (  # noqa: E402
        avg_attention,
    )
    from textsummarization_on_flink_tpu.train import (  # noqa: E402
        distill,
        trainer as trainer_lib,
    )
    from tests.test_distill import (  # noqa: E402
        _ArraysBatch,
        _CycleBatcher,
        copy_task_arrays,
    )
    from tests.test_speculative import make_arrays  # noqa: E402

    hps = HParams(batch_size=4, hidden_dim=16, emb_dim=16, vocab_size=32,
                  max_enc_steps=12, max_dec_steps=8, beam_size=1,
                  min_dec_steps=2, max_oov_buckets=4, mode="decode",
                  model_family="transformer", num_heads=2, enc_layers=1,
                  dec_layers=2, spec_k=2, draft_dec_layers=1,
                  draft_hidden=8, draft_vocab_rank=4,
                  spec_k_adaptive=True, spec_k_min=1, spec_k_max=5)
    hps.validate()
    # a teacher with LEARNABLE greedy behavior: a few hundred steps of
    # the synthetic copy task (the pointer mechanism's native move)
    thps = hps.replace(mode="train")
    tstate = trainer_lib.init_train_state(thps, hps.vocab_size, seed=0)
    tstep = jax.jit(trainer_lib.make_train_step(thps))
    tdata = [copy_task_arrays(make_arrays(hps, 4, seed=1000 + s), hps)
             for s in range(8)]
    for i in range(200):
        tstate, _ = tstep(tstate, tdata[i % 8])
    teacher = jax.device_get(tstate.params)

    dhps = derive_draft_hps(hps)
    fresh = avg_attention.init_params(dhps, hps.vocab_size,
                                      jax.random.PRNGKey(7))
    held = make_arrays(hps, 4, seed=100)
    before = distill.acceptance_rate(teacher, fresh, hps, held)

    batches = [_ArraysBatch(make_arrays(hps, 4, seed=s)) for s in range(8)]
    dt = distill.DistillTrainer(hps, hps.vocab_size,
                                _CycleBatcher(batches), teacher,
                                cache_teacher=True, seed=7)
    dt.distill(200)
    draft = jax.device_get(dt.draft_params())
    after = distill.acceptance_rate(teacher, draft, hps, held)

    ctl = speculative.SpecKController.from_hps(hps)
    out = speculative.run_spec_decode(teacher, draft, hps, held,
                                      controller=ctl)
    greedy = beam_search.run_beam_search(teacher, hps.replace(beam_size=1),
                                         held)
    for b in range(4):
        n = int(greedy.length[b])
        got = list(np.asarray(out.tokens[b])[:n])
        want = list(np.asarray(greedy.tokens[b])[:n])
        assert got == want, (
            f"distilled adaptive spec drifted from greedy on held-out "
            f"row {b}: {got} vs {want}")
    assert after > before, (
        f"distillation did not raise held-out acceptance "
        f"({before:.3f} -> {after:.3f})")
    print(f"distill-spec smoke OK: held-out acceptance "
          f"{before:.2f} -> {after:.2f} after 200 distill steps; "
          f"adaptive spec_k ended at k={ctl.k} "
          f"(mean {ctl.mean_k:.2f} over {ctl.cycles} cycles), "
          f"4 rows token-exact with greedy")


if __name__ == "__main__":
    if "--distill" in sys.argv[1:]:
        distill_main()
    else:
        main()
