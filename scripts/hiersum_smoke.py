"""Hierarchical-summarizer smoke (ISSUE 19): a multi-chunk document
through the REAL pipeline stage on a real tiny model — the no-hardware
proof that the map-reduce long-document path works end to end:

  * framed rows (pipeline/codec.py "doc#i/n") reassemble into one
    document and fan out chunk-by-chunk through a live ServingServer,
    with the reduce pass resolving the parent exactly once;
  * an APPEND frame-set for the same doc id re-summarizes the grown
    document, and every pre-append chunk is served from the front-door
    cache — deduplication by construction: the engine decodes only the
    appended chunks plus one reduce;
  * the reduce output's copy fidelity is observed per revision.

The committed scheduling claims (fan-out makespan vs sequential, the
append cache-hit floor) live in SERVE_SLO.json "hierarchical" and are
enforced by tests/test_serve_slo.py over virtual time; this smoke
proves the THREADED path on a real model.  Wired into scripts/repro.sh.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import shlex  # noqa: E402
import tempfile  # noqa: E402

from textsummarization_on_flink_tpu import obs  # noqa: E402
from textsummarization_on_flink_tpu.checkpoint.checkpointer import (  # noqa: E402
    Checkpointer,
)
from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.vocab import Vocab  # noqa: E402
from textsummarization_on_flink_tpu.pipeline import codec  # noqa: E402
from textsummarization_on_flink_tpu.pipeline.estimator import (  # noqa: E402
    SummarizationModel,
    train_dir_for,
)
from textsummarization_on_flink_tpu.pipeline.io import (  # noqa: E402
    CollectionSink,
    CollectionSource,
    DataTypes,
)
from textsummarization_on_flink_tpu.train import trainer  # noqa: E402

#: 11 words cycled over 8-word chunks: every chunk starts at a distinct
#: phase of the cycle, so no two chunks are textually identical and an
#: intra-document cache hit can never inflate the append-path pins
WORDS = "the quick brown fox jumped over a lazy dog again .".split()
CHUNK_WORDS = 8
DOC_CHUNKS = 4
APPEND_CHUNKS = 2


def _words(start: int, count: int) -> str:
    return " ".join(WORDS[i % len(WORDS)] for i in range(start, start + count))


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="hiersum_smoke_")
    vocab = Vocab(words=WORDS)
    hps = HParams(mode="decode", batch_size=2, hidden_dim=16, emb_dim=8,
                  vocab_size=vocab.size(), max_enc_steps=16,
                  max_dec_steps=6, beam_size=2, min_dec_steps=1,
                  max_oov_buckets=4, serve_max_wait_ms=50.0,
                  serve_max_queue=64, serve_coalesce=True,
                  serve_cache_entries=32, hier_chunk_words=CHUNK_WORDS,
                  log_root=tmp, exp_name="exp")
    # the pipeline stage restores the server's weights from the
    # train-dir hand-off (estimator.py train_dir_for) — seed it with an
    # init state, the same contract a finished training run leaves
    state = trainer.init_train_state(hps, vocab.size(), seed=0)
    Checkpointer(train_dir_for(hps), hps=hps).save(state)

    doc = _words(0, DOC_CHUNKS * CHUNK_WORDS)
    tail = _words(DOC_CHUNKS * CHUNK_WORDS, APPEND_CHUNKS * CHUNK_WORDS)
    frames = codec.frame_document_rows("doc", doc, "ref .", 16)
    frames += codec.frame_document_rows("doc", tail, "", 16)
    rows = [(u, a, "", r) for (u, a, r) in frames]

    model = SummarizationModel()
    (model.set_inference_selected_cols(["uuid", "article", "reference"])
          .set_inference_output_cols(["uuid", "article", "summary",
                                      "reference"])
          .set_inference_output_types([DataTypes.STRING] * 4))
    model.set_inference_hyper_params(shlex.split(hps.to_argv()))
    sink = CollectionSink()
    model.with_vocab(vocab).transform(CollectionSource(rows), sink,
                                      hierarchical=True)

    reg = obs.registry()
    assert [r[0] for r in sink.rows] == ["doc@r1", "doc@r2"], sink.rows
    assert all(r[2] for r in sink.rows), "empty summary out of the reduce"
    docs = int(reg.counter("serve/hier_documents_total").value)
    chunks = int(reg.counter("serve/hier_chunks_total").value)
    hits = int(reg.counter("serve/hier_chunk_cache_hits_total").value)
    reused = int(reg.counter("serve/hier_chunks_reused_total").value)
    decodes = int(reg.counter("serve/completed_total").value)
    partial = int(reg.counter("serve/hier_partial_failures_total").value)
    fid = reg.histogram("serve/hier_copy_fidelity")
    assert docs == 2 and partial == 0, (docs, partial)
    assert chunks == 2 * DOC_CHUNKS + APPEND_CHUNKS, chunks
    # THE append pin: every pre-append chunk cache-hits at submit, and
    # the engine only ever decoded chunks once — plus one reduce per
    # revision (the reduce inputs differ, so both decode)
    assert hits == DOC_CHUNKS, f"expected {DOC_CHUNKS} cache hits, {hits}"
    assert reused == DOC_CHUNKS, reused
    assert decodes == (DOC_CHUNKS + 1) + (APPEND_CHUNKS + 1), decodes
    assert fid.count == 2, fid.count
    print(f"hiersum smoke OK: 2 revisions, {chunks} chunk submits, "
          f"{hits} append cache hits, {decodes} decodes, "
          f"mean copy fidelity {fid.mean:.2f}")


if __name__ == "__main__":
    main()
