#!/usr/bin/env bash
# Poll the TPU tunnel; the moment it is healthy, run the full bench
# sweep (scripts/bench_all.sh -> BENCH_ALL.jsonl).  Intended to run
# inside tmux while the tunnel is flapping:
#     scripts/bench_when_up.sh [interval_seconds]
# Writes sweep progress to stdout; touches BENCH_SWEEP_DONE on success.
# After a complete sweep it stays alive in re-bank mode, appending
# fresh headline rows in later tunnel windows (round-4 review: a
# headline resting on ONE window is one row).
set -u
cd "$(dirname "$0")/.."
INTERVAL="${1:-120}"
unset BENCH_NO_RECORD  # banked rows reach the JSONL via bench.py's append
# an inherited override (e.g. from an ad-hoc probe) would divert the
# banked headline row away from the BENCH_ALL.jsonl this watcher checks
unset BENCH_STALE_FILE
rm -f BENCH_SWEEP_DONE

# ONE probe definition for first-bank and re-bank modes.  40s: a
# healthy tunnel answers in ~10s; the timeout only bounds the DOWN
# case, and a shorter one tightens the probe cycle (catching ~2-min
# windows).  bench_all.sh's mid-sweep abort probe stays at 75s — there
# a false DOWN verdict costs a whole pass.  PYTHONPATH is deliberately
# KEPT: the probe must see the real backend (a scrubbed probe would
# pass on CPU and bank garbage).
probe() {
  timeout 40 python -c "import jax; print(jax.devices())" >/dev/null 2>&1
}

# bank_row TAG MODE TIMEOUT: one headline row via bench.py, which
# self-appends only LIVE successes (stale fallbacks are printed, never
# recorded), run-tagged for bench_latest's newest-per-tag view.
bank_row() {
  BENCH_MODE="$2" BENCH_ATTEMPTS=1 BENCH_TIMEOUT="$3" \
    BENCH_RUN_TAG="$1" python bench.py || true
}

while true; do
  echo "[watch] $(date -u +%H:%M:%S) probing tunnel..."
  if probe; then
    echo "[watch] tunnel UP — banking the quick headline row first"
    # even a ~5-minute tunnel window must bank the headline train number
    # before the 1-2h sweep starts; bench.py self-appends the success
    # (run-tagged train_b16) to BENCH_ALL.jsonl.  Once that row is live,
    # skip straight to the sweep (which banks rows incrementally).
    if env PYTHONPATH= python - <<'PYEOF' 2>/dev/null
import sys
sys.path.insert(0, "scripts")
from bench_latest import latest_by_tag
rec = latest_by_tag("BENCH_ALL.jsonl").get("train_b16")
sys.exit(0 if rec is not None and "error" not in rec
         and not rec.get("stale") else 1)
PYEOF
    then
      echo "[watch] headline row already live — straight to the sweep"
    else
      bank_row train_b16 train 300
    fi
    echo "[watch] starting full sweep"
    bash scripts/bench_all.sh
    # bench_all.sh never exits nonzero (error rows become stubs in the
    # jsonl), so judge success from the records: every sweep tag's
    # NEWEST record must be a live measurement (no error, not stale).
    # A tunnel drop mid-sweep leaves error rows -> retry next probe
    # (append-only file: reruns overwrite by recency, newest wins).
    # one definition of "newest record per tag": bench_latest.py
    # (max captured_at, live beats stale on ties) — so a live row banked
    # earlier in this window counts even if a later re-run timed out.
    # Scrubbed PYTHONPATH: the check needs no TPU plugin, and the axon
    # sitecustomize hook is slow/wedge-prone when the tunnel is down.
    if env PYTHONPATH= python - <<'PYEOF'
import re
import sys
sys.path.insert(0, "scripts")
from bench_latest import latest_by_tag  # ONE definition of newest-per-tag

live = {tag for tag, rec in latest_by_tag("BENCH_ALL.jsonl").items()
        if "error" not in rec and not rec.get("stale")}
# the sweep script's run lines ARE the tag list (single source: a row
# added there is automatically required here)
tags = re.findall(r"^run\s+(\S+)", open("scripts/bench_all.sh").read(),
                  re.M)
assert tags, "no run lines found in scripts/bench_all.sh"
bad = [t for t in tags if t not in live]
if bad:
    print(f"[watch] incomplete sweep rows: {bad}", file=sys.stderr)
    sys.exit(1)
PYEOF
    then
      echo "[watch] sweep complete — all rows live"
      touch BENCH_SWEEP_DONE
      # version the captured numbers immediately: an unattended success
      # must survive even if nothing else touches the repo afterwards.
      # Pathspec commit (-o): never sweep unrelated staged work into a
      # bench-labelled commit; errors go to the log, not /dev/null.
      if git commit -q -o BENCH_ALL.jsonl \
          -m "Bench sweep: on-hardware numbers captured (watcher auto-commit)"
      then
        echo "[watch] BENCH_ALL.jsonl committed"
      else
        echo "[watch] auto-commit FAILED (rc=$?) — records remain in the working tree"
      fi
      # the window may still be open: capture the TPU-compiled roofline
      # attribution + a profiler trace (scripts/capture_window_extras.sh,
      # idempotent).  Strictly after the rows are committed — the
      # diagnostics must never cost a banked number.
      bash scripts/capture_window_extras.sh \
        || echo "[watch] window extras incomplete (rc=$?)"
      # robustness mode: keep probing at the NORMAL cadence (windows are
      # ~2 min — one probe per cooldown would catch ~none) and re-bank
      # the two headline rows (train throughput, decode p50) when a
      # window is found; the cooldown gates SUCCESSFUL re-banks only, so
      # each appended record is an independent window's measurement.
      COOLDOWN="${REBANK_COOLDOWN:-7200}"
      echo "[watch] entering re-bank mode (probe every ${INTERVAL}s; at most one re-bank per ${COOLDOWN}s)"
      last_rebank=0
      while true; do
        now=$(date +%s)
        if [ $((now - last_rebank)) -ge "$COOLDOWN" ]; then
          echo "[watch] $(date -u +%H:%M:%S) re-bank probe..."
          if probe; then
            bank_row train_b16 train 300
            # 1200s to match bench_all.sh's decode rows (advisor r5 #2):
            # a cold first compile exceeds 600s, and a child killed
            # mid-compile writes nothing to the persistent compile cache
            # — decode re-banking would then starve on every window
            bank_row decode_b4 decode 1200
            # stale fallbacks are printed, never self-appended, so the
            # file only ever gains LIVE re-measurements here
            if ! git diff --quiet -- BENCH_ALL.jsonl; then
              if git commit -q -o BENCH_ALL.jsonl \
                  -m "Re-banked headline rows in a later tunnel window (watcher auto-commit)"
              then
                echo "[watch] re-banked rows committed"
              else
                echo "[watch] re-bank auto-commit FAILED (rc=$?) — records remain in the working tree"
              fi
              last_rebank=$(date +%s)
            fi
          fi
        fi
        sleep "$INTERVAL"
      done
    fi
    echo "[watch] sweep incomplete; will retry"
  fi
  sleep "$INTERVAL"
done
