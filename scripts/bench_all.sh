#!/usr/bin/env bash
# Capture the full benchmark sweep on the current backend into one JSONL
# file (default BENCH_ALL.jsonl).  Each line is bench.py's single JSON
# record plus a "run" tag.  Used to (re)populate BASELINE.md's measured
# table whenever the TPU tunnel is healthy:
#
#     scripts/bench_all.sh [out.jsonl]
#
# Runs: train at reference batch 16 (with Pallas-kernel and unroll=1
# A/B rows), train at batch 64, train scaled (hidden 512 / enc 800),
# transformer-family train, decode latency for BOTH families,
# attention + flash kernel A/Bs, host input pipeline.
set -uo pipefail

OUT="${1:-BENCH_ALL.jsonl}"
case "$OUT" in /*) ;; *) OUT="$PWD/$OUT" ;; esac  # resolve before the cd
cd "$(dirname "$0")/.."
# APPEND, never truncate: bench.py's stale fallback serves the NEWEST
# matching record (max captured_at; live beats stale on ties), so older
# lines are harmless — but truncating would destroy the very records the
# fallback needs if the tunnel drops mid-sweep.  Each record carries
# captured_at + config_fingerprint; summarize the latest per tag with
# scripts/bench_latest.py.
touch "$OUT"
# the stale fallback must read the SAME file this sweep writes
export BENCH_STALE_FILE="$OUT"
# successful rows reach $OUT only through bench.py's self-append; an
# inherited opt-out would silently discard every measured row
unset BENCH_NO_RECORD

# one attempt per row: the bench_when_up.sh watcher retries whole
# passes, so per-row retries would just slow a dead-tunnel pass down
export BENCH_ATTEMPTS="${BENCH_ATTEMPTS:-1}"
# tunnel windows have been observed as short as ~2 min; a warm-cache row
# measures in ~60-90s, so 360s covers a cold compile while capping the
# time a mid-window tunnel drop can burn before the early-abort probe
export BENCH_TIMEOUT="${BENCH_TIMEOUT:-360}"

run() {
  local tag="$1"; shift
  # incremental banking: rows whose NEWEST record is already a live
  # measurement are skipped, so each short tunnel window adds NEW rows
  # instead of re-measuring banked ones.  BENCH_FORCE=1 re-measures all.
  if [ -z "${BENCH_FORCE:-}" ] && env PYTHONPATH= python - "$tag" "$OUT" <<'PYEOF' 2>/dev/null
import sys
sys.path.insert(0, "scripts")
from bench_latest import latest_by_tag
rec = latest_by_tag(sys.argv[2]).get(sys.argv[1])
live = rec is not None and "error" not in rec and not rec.get("stale")
sys.exit(0 if live else 1)
PYEOF
  then
    echo "== $tag (already live — skipped; BENCH_FORCE=1 re-measures)" >&2
    return 0
  fi
  echo "== $tag" >&2
  local line
  # bench.py itself appends successful records (run-tagged via
  # BENCH_RUN_TAG) to $OUT — single writer, so an interrupted sweep can
  # never lose a banked number.  The sweep only appends error/stale
  # stubs, which the watcher's completeness check keys off.
  line="$(env BENCH_RUN_TAG="$tag" "$@" python bench.py 2>/dev/null | tail -1)"
  # helper invocations scrub PYTHONPATH: the axon sitecustomize hook
  # costs ~1.8s per interpreter start (and can wedge when the tunnel is
  # down).  `python bench.py` and the tunnel probe below KEEP the
  # inherited path — the bench child needs the plugin to reach the TPU,
  # and the probe must see the real backend or it would silently pass
  # on CPU and the dead-tunnel early-abort would never fire
  if [ -z "$line" ]; then
    echo "{\"run\": \"$tag\", \"error\": \"no output\"}" >> "$OUT"
  elif printf '%s\n' "$line" | env PYTHONPATH= python -c "
import json,sys
rec = json.loads(sys.stdin.read())
sys.exit(0 if ('error' in rec or rec.get('stale')) else 1)" 2>/dev/null; then
    printf '%s\n' "$line" | env PYTHONPATH= python -c "
import json,sys
rec = json.loads(sys.stdin.read()); rec['run'] = '$tag'
print(json.dumps(rec))" >> "$OUT"
  elif ! grep -qF "$line" "$OUT"; then
    # bench.py appends successes itself, printing the identical JSON it
    # recorded — if the line is missing, the self-append failed (its
    # stderr warning was discarded above); do not lose the measurement
    echo "[sweep] self-append missing for '$tag'; appending fallback" >&2
    printf '%s\n' "$line" >> "$OUT"
  fi
  # a timed-out row usually means the tunnel died mid-sweep; probe once
  # and abort the pass early if so (the watcher retries the whole pass —
  # burning 10-20 min per remaining row on a dead tunnel helps no one)
  if printf '%s' "$line" | grep -q "timed out"; then
    if ! timeout 75 python -c "import jax; jax.devices()" >/dev/null 2>&1
    then
      echo "[sweep] tunnel down after '$tag' — aborting this pass" >&2
      exit 3
    fi
  fi
}

# Ordered by value-per-minute of a (possibly short) tunnel window: the
# two headline numbers first (train throughput, decode serving latency),
# then the second family + e2e, then the A/B lever rows.  Already-live
# rows are skipped (see run()), so this is the order NEW rows bank in.
run train_b16            BENCH_MODE=train
run decode_b4            BENCH_MODE=decode
run train_transformer    BENCH_MODE=train BENCH_FAMILY=transformer
run trainer_e2e          BENCH_MODE=trainer
run decode_b1            BENCH_MODE=decode BENCH_BATCH=1
run train_b64            BENCH_MODE=train BENCH_BATCH=64
run decode_chunked       BENCH_MODE=decode TS_BEAM_LOOP=chunked
run decode_while         BENCH_MODE=decode TS_BEAM_LOOP=while
run decode_transformer   BENCH_MODE=decode BENCH_FAMILY=transformer
run train_b16_unroll1    BENCH_MODE=train BENCH_UNROLL=1
run train_b16_unroll16   BENCH_MODE=train BENCH_UNROLL=16
run train_b16_pallas     BENCH_MODE=train TS_PALLAS=on
run train_b16_remat      BENCH_MODE=train BENCH_REMAT=1
run train_scaled         BENCH_MODE=train BENCH_PRESET=scaled
run train_transformer_flash BENCH_MODE=train BENCH_FAMILY=transformer TS_FLASH=on
run trainer_e2e_spd1     BENCH_MODE=trainer BENCH_SPD=1
run attention_ab         BENCH_MODE=attention
run flash_ab             BENCH_MODE=flash
run input_pipeline       BENCH_MODE=input

echo "wrote $(wc -l < "$OUT") records to $OUT" >&2
