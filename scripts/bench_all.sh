#!/usr/bin/env bash
# Capture the full benchmark sweep on the current backend into one JSONL
# file (default BENCH_ALL.jsonl).  Each line is bench.py's single JSON
# record plus a "run" tag.  Used to (re)populate BASELINE.md's measured
# table whenever the TPU tunnel is healthy:
#
#     scripts/bench_all.sh [out.jsonl]
#
# Runs: train at reference batch 16 (with Pallas-kernel, unroll, remat
# and byte-diet A/B rows), train at batch 64, train scaled (hidden 512 /
# enc 800), transformer-family train, decode latency for BOTH families,
# attention + flash kernel A/Bs, host input pipeline, and the CPU-only
# cost-analysis byte accounting (BENCH_MODE=bytes).
set -uo pipefail

OUT="${1:-BENCH_ALL.jsonl}"
case "$OUT" in /*) ;; *) OUT="$PWD/$OUT" ;; esac  # resolve before the cd
cd "$(dirname "$0")/.."
# APPEND, never truncate: bench.py's stale fallback serves the NEWEST
# matching record (max captured_at; live beats stale on ties), so older
# lines are harmless — but truncating would destroy the very records the
# fallback needs if the tunnel drops mid-sweep.  Each record carries
# captured_at + config_fingerprint; summarize the latest per tag with
# scripts/bench_latest.py.
touch "$OUT"
# the stale fallback must read the SAME file this sweep writes
export BENCH_STALE_FILE="$OUT"
# successful rows reach $OUT only through bench.py's self-append; an
# inherited opt-out would silently discard every measured row
unset BENCH_NO_RECORD

# one attempt per row: the bench_when_up.sh watcher retries whole
# passes, so per-row retries would just slow a dead-tunnel pass down
export BENCH_ATTEMPTS="${BENCH_ATTEMPTS:-1}"
# tunnel windows have been observed as short as ~2 min; a warm-cache row
# measures in ~60-90s, so 360s covers a cold compile while capping the
# time a mid-window tunnel drop can burn before the early-abort probe.
# ADVICE r4 (medium): the cap is mode-aware — full-scale beam-search
# while/chunked first compiles can exceed 360s (bench.py's own decode
# default is 1200s), and a child killed mid-compile writes nothing to
# the persistent compile cache, so a flat cap would time those rows out
# identically on every pass; their run lines below pass a longer
# per-row BENCH_TIMEOUT instead.
export BENCH_TIMEOUT="${BENCH_TIMEOUT:-360}"

# set by run() whenever a row banked a LIVE measurement; ratio sections
# reset it to detect "this pass banked a new numerator here".
# SKIPPED_TAGS collects the skipped-as-live rows so pair_denominator
# only re-measures a denominator that was NOT already measured in this
# same pass/window.
DID_MEASURE=0
SKIPPED_TAGS=""

# pair_denominator TAG ENV...: A/B lever rows are ratioed against a
# denominator row, and PERF.md's ±3%/1.05x kill rules assume both sides
# of the ratio came from the SAME tunnel window (ADVICE r4: a banked
# denominator may be days and a different tunnel/compile-cache state
# older).  Call after a ratio section: if the section banked a new
# numerator while its denominator was skipped-as-live, re-measure the
# denominator once, in the same window.
pair_denominator() {
  local denom="$1"; shift
  if [ "$DID_MEASURE" = 1 ]; then
    case "$SKIPPED_TAGS" in *" $denom "*)
      echo "[sweep] ratio row(s) banked but $denom was skipped-as-live — re-measuring the denominator in the same window" >&2
      BENCH_FORCE=1 run "$denom" "$@"
      ;;
    esac
  fi
}

run() {
  local tag="$1"; shift
  # incremental banking: rows whose NEWEST record is already a live
  # measurement are skipped, so each short tunnel window adds NEW rows
  # instead of re-measuring banked ones.  BENCH_FORCE=1 re-measures all.
  # ADVICE r4: the record must also carry the fingerprint bench.py would
  # compute for THIS row's env — after a perf-default flip (say the
  # unroll default moves), a banked old-config record would otherwise be
  # skipped forever and served as the current headline, the exact
  # substitution bench.py's stale fallback refuses via fingerprint match.
  if [ -z "${BENCH_FORCE:-}" ]; then
    # exit 0 = live (skip), 1 = needs measuring, 2 = the check itself
    # crashed — warn and fall through to measuring, so a broken check
    # degrades to re-measuring WITH a diagnostic instead of silently
    # disabling incremental banking (stderr kept for the same reason)
    env PYTHONPATH= "$@" python - "$tag" "$OUT" <<'PYEOF'
import sys
try:
    sys.path.insert(0, "scripts"); sys.path.insert(0, ".")
    from bench_latest import latest_by_tag
    import bench
    rec = latest_by_tag(sys.argv[2]).get(sys.argv[1])
    live = (rec is not None and "error" not in rec and not rec.get("stale")
            and rec.get("config_fingerprint") == bench._config_fingerprint())
except Exception as exc:  # noqa: BLE001
    print(f"liveness check failed: {type(exc).__name__}: {exc}",
          file=sys.stderr)
    sys.exit(2)
sys.exit(0 if live else 1)
PYEOF
    case $? in
      0)
        echo "== $tag (already live — skipped; BENCH_FORCE=1 re-measures)" >&2
        SKIPPED_TAGS="$SKIPPED_TAGS $tag "
        return 0 ;;
      2) echo "[sweep] liveness check crashed for '$tag' — re-measuring" >&2 ;;
    esac
  fi
  echo "== $tag" >&2
  local line
  # bench.py itself appends successful records (run-tagged via
  # BENCH_RUN_TAG) to $OUT — single writer, so an interrupted sweep can
  # never lose a banked number.  The sweep only appends error/stale
  # stubs, which the watcher's completeness check keys off.
  line="$(env BENCH_RUN_TAG="$tag" "$@" python bench.py 2>/dev/null | tail -1)"
  # helper invocations scrub PYTHONPATH: the axon sitecustomize hook
  # costs ~1.8s per interpreter start (and can wedge when the tunnel is
  # down).  `python bench.py` and the tunnel probe below KEEP the
  # inherited path — the bench child needs the plugin to reach the TPU,
  # and the probe must see the real backend or it would silently pass
  # on CPU and the dead-tunnel early-abort would never fire
  if [ -z "$line" ]; then
    echo "{\"run\": \"$tag\", \"error\": \"no output\"}" >> "$OUT"
  else
    # classify the child's last line (advisor r5 #4): 0 = error/stale
    # record, 1 = live measurement, 2 = unparseable.  A crashed
    # classifier used to read as "live" and arm the denominator pairing
    # off garbage.
    printf '%s\n' "$line" | env PYTHONPATH= python -c "
import json,sys
try:
    rec = json.loads(sys.stdin.read())
except ValueError:
    sys.exit(2)
if not isinstance(rec, dict) or 'metric' not in rec:
    sys.exit(2)
sys.exit(0 if ('error' in rec or rec.get('stale')) else 1)" 2>/dev/null
    case $? in
      0)
        printf '%s\n' "$line" | env PYTHONPATH= python -c "
import json,sys
rec = json.loads(sys.stdin.read()); rec['run'] = '$tag'
print(json.dumps(rec))" >> "$OUT"
        ;;
      1)
        # a LIVE measurement banked (only this arms the paired-denominator
        # re-measure — an error/stale/unparseable row pairs with nothing)
        DID_MEASURE=1
        if ! grep -qF "$line" "$OUT"; then
          # bench.py appends successes itself, printing the identical JSON
          # it recorded — if the line is missing, the self-append failed
          # (its stderr warning was discarded above); do not lose the
          # measurement
          echo "[sweep] self-append missing for '$tag'; appending fallback" >&2
          printf '%s\n' "$line" >> "$OUT"
        fi
        ;;
      *)
        # garbage on stdout (partial write, interleaved noise): append a
        # typed error stub — never the raw line, which would poison the
        # JSONL for every downstream reader — and leave DID_MEASURE alone
        echo "[sweep] unparseable bench output for '$tag'" >&2
        env PYTHONPATH= python -c "
import json,sys
print(json.dumps({'run': sys.argv[1],
                  'error': 'unparseable bench output'}))" "$tag" >> "$OUT"
        ;;
    esac
  fi
  # a timed-out row usually means the tunnel died mid-sweep; probe once
  # and abort the pass early if so (the watcher retries the whole pass —
  # burning 10-20 min per remaining row on a dead tunnel helps no one)
  if printf '%s' "$line" | grep -q "timed out"; then
    if ! timeout 75 python -c "import jax; jax.devices()" >/dev/null 2>&1
    then
      echo "[sweep] tunnel down after '$tag' — aborting this pass" >&2
      exit 3
    fi
  fi
}

# Test hook (tests/test_bench_scripts.py): exercise run()'s
# classification/append contract on ONE row against a stubbed bench.py,
# then report whether the row armed the denominator pairing — instead of
# running the sweep.  The hook keeps the tested code EXACTLY the shipped
# run()/pair_denominator definitions above.
if [ -n "${BENCH_SWEEP_SINGLE:-}" ]; then
  run "$BENCH_SWEEP_SINGLE"
  echo "DID_MEASURE=$DID_MEASURE"
  exit 0
fi

# Ordered by value-per-minute of a (possibly short) tunnel window: the
# two headline numbers first (train throughput, decode serving latency),
# then the second family + e2e, then the A/B lever rows.  Already-live
# rows are skipped (see run()), so this is the order NEW rows bank in.
# decode rows all get bench.py's own 1200s decode default instead of
# the 360s sweep cap: the first full-scale beam-search compile (scan or
# while) can exceed 360s, and a child killed mid-compile writes nothing
# to the persistent compile cache — the row would then time out
# identically on every pass (ADVICE r4).  Once compiled, the warm-cache
# row measures in ~60-90s; a tunnel death mid-row is bounded by the
# early-abort probe in run().
run train_b16            BENCH_MODE=train
run decode_b4            BENCH_MODE=decode BENCH_TIMEOUT=1200
run train_transformer    BENCH_MODE=train BENCH_FAMILY=transformer
run trainer_e2e          BENCH_MODE=trainer
# --- decode A/B lever rows, ratioed against decode_b4 (loop-strategy
# choice + batch-amortization): same-window denominator pairing
DID_MEASURE=0
run decode_b1            BENCH_MODE=decode BENCH_BATCH=1 BENCH_TIMEOUT=1200
run decode_chunked       BENCH_MODE=decode TS_BEAM_LOOP=chunked BENCH_TIMEOUT=1200
run decode_while         BENCH_MODE=decode TS_BEAM_LOOP=while BENCH_TIMEOUT=1200
pair_denominator decode_b4 BENCH_MODE=decode BENCH_TIMEOUT=1200
run decode_transformer   BENCH_MODE=decode BENCH_FAMILY=transformer BENCH_TIMEOUT=1200
# --- train A/B lever rows, ratioed against train_b16.  EVERY row whose
# PERF.md band is stated against train_b16 sits before the
# pair_denominator call (advisor r5 #1: train_scaled and
# trainer_e2e_spd1 used to bank after it, so their ratios could pair
# with a days-old denominator from a different tunnel window).
DID_MEASURE=0
run train_b16_unroll1    BENCH_MODE=train BENCH_UNROLL=1
run train_b16_unroll16   BENCH_MODE=train BENCH_UNROLL=16
run train_b16_pallas     BENCH_MODE=train TS_PALLAS=on
run train_b16_remat      BENCH_MODE=train BENCH_REMAT=1
run train_b16_losschunk  BENCH_MODE=train BENCH_LOSS_CHUNK=25
run train_b16_bytediet   BENCH_MODE=train BENCH_LOSS_CHUNK=25 BENCH_OPT_DTYPE=bfloat16
run train_b64            BENCH_MODE=train BENCH_BATCH=64
run train_scaled         BENCH_MODE=train BENCH_PRESET=scaled
run trainer_e2e_spd1     BENCH_MODE=trainer BENCH_SPD=1
pair_denominator train_b16 BENCH_MODE=train
# --- transformer lever row, ratioed against train_transformer (advisor
# r5 #1: the flash A/B needs its own same-window denominator pairing)
DID_MEASURE=0
run train_transformer_flash BENCH_MODE=train BENCH_FAMILY=transformer TS_FLASH=on
pair_denominator train_transformer BENCH_MODE=train BENCH_FAMILY=transformer
# --- speculative quality tier (ISSUE 10): the spec row carries the
# measured acceptance rate + implied expected speedup next to its
# p50/p99; greedy is its same-window comparison baseline (the tier
# that spec is token-exact with).  Transformer family: the draft is
# the mapped AAN bootstrap, the real serving recipe.
DID_MEASURE=0
run serve_spec_tier      BENCH_MODE=serve BENCH_FAMILY=transformer BENCH_SERVE_TIER=spec BENCH_TIMEOUT=1200
pair_denominator serve_greedy_tier BENCH_MODE=serve BENCH_FAMILY=transformer BENCH_SERVE_TIER=greedy BENCH_TIMEOUT=1200
run attention_ab         BENCH_MODE=attention
run flash_ab             BENCH_MODE=flash
run input_pipeline       BENCH_MODE=input
# host-only byte accounting (PERF.md byte diet): compiles ref-scale
# cost-analysis programs on CPU — long first compile, so it gets its own
# generous cap; a down tunnel cannot affect it (CPU-forced child)
run bytes_cpu            BENCH_MODE=bytes BENCH_TIMEOUT=3600

echo "wrote $(wc -l < "$OUT") records to $OUT" >&2
