#!/usr/bin/env python
"""Performance attribution report (ISSUE 16; OBSERVABILITY.md
"Performance attribution").

Two sources, one table:

  * ``events.jsonl`` (obs/export.py EventSink): ``{"kind": "span"}``
    records aggregated per span name — count, total/mean/max wall —
    the offline view of where a run's time went;
  * ``--url http://127.0.0.1:<port>/profile``: the live profiler
    payload (obs/profile.py) — phase ledger, wall/coverage accounting,
    compile ledger (warm set, per-site budgets, storm state),
    divergence table, and the top-k slowest dispatches with trace
    exemplar ids that paste straight into
    ``scripts/trace_summary.py --request``.

    python scripts/perf_report.py logs/exp/serve
    python scripts/perf_report.py logs/exp/serve --json
    python scripts/perf_report.py --url http://127.0.0.1:9100/profile
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import urllib.request
from collections import defaultdict


def find_event_files(root: str) -> list:
    if os.path.isfile(root):
        return [root]
    return sorted(glob.glob(os.path.join(root, "**", "events.jsonl"),
                            recursive=True))


def span_table(paths: list) -> list:
    """Aggregate span records per name: [{name, count, total_ms,
    mean_ms, max_ms}], sorted by total descending."""
    agg: dict = defaultdict(lambda: [0, 0.0, 0.0])  # count, total, max
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # half-written tail line of a live run
                if rec.get("kind") != "span":
                    continue
                ms = float(rec.get("dur_us", 0)) / 1e3
                row = agg[rec.get("name", "?")]
                row[0] += 1
                row[1] += ms
                if ms > row[2]:
                    row[2] = ms
    return [{"name": name, "count": c,
             "total_ms": round(total, 3),
             "mean_ms": round(total / c, 3) if c else 0.0,
             "max_ms": round(mx, 3)}
            for name, (c, total, mx) in
            sorted(agg.items(), key=lambda kv: -kv[1][1])]


def fetch_profile(url: str) -> dict:
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


def render_spans(rows: list, top: int) -> None:
    print(f"{'span':<40} {'count':>7} {'total_ms':>12} "
          f"{'mean_ms':>10} {'max_ms':>10}")
    for row in rows[:top]:
        print(f"{row['name']:<40} {row['count']:>7} "
              f"{row['total_ms']:>12.3f} {row['mean_ms']:>10.3f} "
              f"{row['max_ms']:>10.3f}")


def render_profile(payload: dict, top: int) -> None:
    if not payload.get("installed"):
        print("profiler not installed on the scraped registry")
        return
    print(f"phase coverage: {payload.get('coverage', 0.0):.3f} "
          f"(sum of phases / sum of walls)")
    print(f"\n{'phase':<28} {'count':>7} {'total_s':>10} {'mean_ms':>10}")
    for row in payload.get("phases", []):
        print(f"{row['phase']:<28} {row['count']:>7} "
              f"{row['total_s']:>10.4f} {row['mean_ms']:>10.3f}")
    ledger = payload.get("compile_ledger", {})
    print(f"\ncompile ledger: warm set {ledger.get('warm_set', 0)}"
          + (", STORM: " + json.dumps(ledger["storm"])
             if ledger.get("storm") else ""))
    for site, st in sorted(ledger.get("sites", {}).items()):
        budget = st.get("budget")
        print(f"  {site:<28} compiles {st['compiles']:>3} "
              f"hits {st['hits']:>6} budget "
              f"{budget if budget is not None else '-':>3} "
              f"keys {st['keys']}")
    div = payload.get("divergence", [])
    if div:
        print("\ndivergence sentinel:")
        for row in div:
            print(f"  {row['site']}[{row['key']}] drift {row['drift']} "
                  f"achieved {row['achieved_bytes_per_s']:.3g} B/s "
                  f"baseline {row['baseline_bytes_per_s']:.3g} B/s")
    slowest = payload.get("slowest", [])[:top]
    if slowest:
        print("\nslowest dispatches (trace ids feed "
              "trace_summary.py --request):")
        for row in slowest:
            print(f"  {row['phase']:<28} {1e3 * row['dur_s']:>10.3f} ms "
                  f"trace {row.get('trace_id') or '-'}")
    for note in payload.get("notes", []):
        print(f"note: {json.dumps(note)}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("root", nargs="?", default=None,
                    help="events.jsonl file or directory holding one")
    ap.add_argument("--url", default=None,
                    help="live /profile endpoint to fetch")
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)
    if args.root is None and args.url is None:
        ap.error("give an events.jsonl root and/or --url")
    out: dict = {}
    if args.root is not None:
        paths = find_event_files(args.root)
        if not paths:
            print(f"no events.jsonl under {args.root}", file=sys.stderr)
            return 2
        out["spans"] = span_table(paths)
        out["files"] = paths
    if args.url is not None:
        out["profile"] = fetch_profile(args.url)
    if args.as_json:
        json.dump(out, sys.stdout, indent=2)
        print()
        return 0
    if "spans" in out:
        print(f"span self-time over {len(out['files'])} events.jsonl "
              f"file(s):")
        render_spans(out["spans"], args.top)
    if "profile" in out:
        if "spans" in out:
            print()
        render_profile(out["profile"], args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
