#!/usr/bin/env python
"""Summarize a trace capture into the op-level table BASELINE.md's
arbitration asks for (top ops by device time, per lane).

    python scripts/trace_summary.py [exp/trace_r05] [--top 15] [--json]
    python scripts/trace_summary.py logs/exp/train/events.jsonl
    python scripts/trace_summary.py exp/serve/events.jsonl --request u17

Two capture kinds, one tool (ISSUE 1 satellite):

  * Chrome-trace JSON (`*.trace.json[.gz]`) that `jax.profiler` writes
    next to the xplane file (TensorBoard not required — the rig has no
    tensorboard_plugin_profile, so this parses the portable format);
  * the unified obs `events.jsonl` (obs/export.py EventSink +
    SummaryWriter scalars in one file): `{"kind": "span", ...}` records
    are treated as complete events; scalar/step records are skipped.

Events are grouped into lanes (one per process/pid: TPU device lanes,
host threads); within a lane, complete events ('ph': 'X') are summed by
name.  Python host-frame events (names like `$threading.py:323 wait`)
are dropped from per-op tables by default — on a device lane the names
are XLA ops/fusions, which is the table that names the bottleneck op
(e.g. the transformer <6%-MFU escalation in BASELINE.md).

Directory arguments prefer profiler captures when both kinds are
present (the established behavior); point at the events.jsonl file
directly — or a directory holding only events.jsonl — for span tables.

``--request <uuid-or-trace_id>`` switches to the request-timeline view
(ISSUE 9): the ``{"kind": "request"}`` lifecycle events the serve path
emits (enqueue -> admit -> slot -> finish -> resolve, OBSERVABILITY.md
"Request-scoped tracing") are reconstructed for one uuid, printed with
per-phase durations (queue wait vs resident/decode vs resolve fan-out),
plus any spans stamped with the request's trace_id.  A TRACE id works
too (ISSUE 15): paste a histogram bucket's exemplar straight off
``/metrics`` or ``/exemplars`` and the fat-p99 request's full
cross-replica timeline comes back.
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
from collections import defaultdict


def find_trace_files(root: str) -> list:
    """Candidate captures under `root`: profiler Chrome traces when any
    exist (established behavior), else unified obs events.jsonl files."""
    if os.path.isfile(root):
        return [root]
    pats = [os.path.join(root, "**", "*.trace.json.gz"),
            os.path.join(root, "**", "*.trace.json")]
    files: list = []
    for p in pats:
        files.extend(glob.glob(p, recursive=True))
    if files:
        return sorted(files)
    return sorted(glob.glob(os.path.join(root, "**", "events.jsonl"),
                            recursive=True))


def _events_jsonl_to_trace(path: str) -> dict:
    """Unified events.jsonl -> the Chrome-trace dict shape summarize()
    consumes.  Span records become 'X' complete events; SummaryWriter
    scalar records ({"step": N, ...}) and snapshot dumps are skipped."""
    events: list = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # half-written tail line of a live run
            if not isinstance(rec, dict) or rec.get("kind") != "span":
                continue
            events.append({
                "ph": "X",
                "name": rec.get("name", "?"),
                "ts": float(rec.get("ts_us", 0)),
                "dur": float(rec.get("dur_us", 0)),
                "pid": rec.get("pid", 0),
                "tid": rec.get("tid", 0),
            })
    return {"traceEvents": events}


def load_events(path: str) -> dict:
    if path.endswith(".jsonl"):
        return _events_jsonl_to_trace(path)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def summarize(trace: dict, include_host_frames: bool = False) -> list:
    """Per-lane op-time summary, one lane per (pid, tid) thread line.

    Grouping by pid alone would double-count: the profiler's export
    gives a device several lines (e.g. a module/step-level line whose
    events span the same wall time as the per-op line), so summing
    across a pid's tids inflates busy time and the enclosing module
    event would top the \"op\" table.  Per-thread lanes keep each line
    honest; the op line is the one whose names are XLA ops/fusions.

    Returns [{lane, pid, tid, busy_us, ops: [{name, total_us, count}]}]
    sorted by lane busy time, descending.
    """
    proc_names: dict = {}
    thread_names: dict = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            proc_names[e.get("pid")] = e.get("args", {}).get("name", "?")
        elif e.get("name") == "thread_name":
            thread_names[(e.get("pid"), e.get("tid"))] = (
                e.get("args", {}).get("name", "?"))

    per_lane: dict = defaultdict(lambda: defaultdict(lambda: [0.0, 0]))
    busy: dict = defaultdict(float)
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        name = e.get("name", "?")
        if not include_host_frames and name.startswith("$"):
            continue  # python host frames, not ops
        dur = float(e.get("dur", 0.0))
        key = (e.get("pid"), e.get("tid"))
        cell = per_lane[key][name]
        cell[0] += dur
        cell[1] += 1
        busy[key] += dur
    out = []
    for (pid, tid), ops in per_lane.items():
        proc = proc_names.get(pid, str(pid))
        thread = thread_names.get((pid, tid))
        out.append({
            "lane": f"{proc}/{thread}" if thread else proc,
            "pid": pid,
            "tid": tid,
            "busy_us": round(busy[(pid, tid)], 1),
            "ops": sorted(
                ({"name": n, "total_us": round(t, 1), "count": c}
                 for n, (t, c) in ops.items()),
                key=lambda o: -o["total_us"]),
        })
    out.sort(key=lambda lane: -lane["busy_us"])
    return out


def _iter_jsonl(path: str):
    """Parsed records of one events.jsonl (bad/half-written lines
    skipped, same tolerance as _events_jsonl_to_trace)."""
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                yield rec


def request_timeline(paths, uuid: str) -> dict:
    """One request's reconstructed timeline from unified events.jsonl
    file(s): its lifecycle events (by uuid — or by trace_id, so a
    histogram EXEMPLAR off /metrics or /exemplars pastes straight in,
    ISSUE 15), the spans sharing its trace_id, and the per-phase
    durations.

    Returns {"uuid", "trace_id", "events": [...], "spans": [...],
    "phases": {...}, "children": [...]} — events/spans sorted by ts_us.
    Phases (ms): ``queue`` = enqueue->admit, ``resident`` =
    admit->finish (or ->resolve when no finish event exists, e.g. a
    queue eviction), ``resolve`` = finish->resolve, ``total`` =
    enqueue->resolve.

    ``children`` (ISSUE 19): when the uuid is a HIERARCHICAL document
    request (serve/hiersum.py), every chunk and reduce sub-request
    shares the parent's trace_id and carries a ``hier_chunk`` /
    ``hier_reduce`` lifecycle event — those sub-requests come back as
    one entry each (chunk index, bucket, tier, cache_hit, resident ms
    from the child's own admit->finish window) so the whole fan-out
    tree reconstructs from one events.jsonl.  Empty for plain requests.
    """
    # pass 1: the uuid's (or exemplar trace_id's) request events (tiny
    # result set).  Buffering the file's spans instead would hold
    # memory proportional to the whole capture just to answer one uuid.
    events: list = []
    for path in paths:
        events.extend(r for r in _iter_jsonl(path)
                      if r.get("kind") == "request"
                      and (r.get("uuid") == uuid
                           or r.get("trace_id") == uuid))
    events.sort(key=lambda r: r.get("ts_us", 0))
    # the argument may have been a trace_id: resolve the uuid the
    # matched lifecycle events actually carry (first one wins — a
    # trace_id maps to one routed request by construction)
    uuids = [r["uuid"] for r in events if r.get("uuid")]
    if uuids and uuid not in uuids:
        uuid = uuids[0]
    trace_ids = {r["trace_id"] for r in events if r.get("trace_id")}
    trace_id = sorted(trace_ids)[0] if trace_ids else None
    # pass 2 (only when the uuid matched a trace): spans sharing its
    # trace_ids.  A cheap substring pre-filter skips the JSON decode
    # for the vast majority of non-matching lines, so the second pass
    # costs ~one scan, with memory bounded by the MATCHING spans.
    spans: list = []
    if trace_ids:
        for path in paths:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    if '"span"' not in line or not any(
                            tid in line for tid in trace_ids):
                        continue
                    try:
                        r = json.loads(line)
                    except ValueError:
                        continue
                    if (isinstance(r, dict) and r.get("kind") == "span"
                            and r.get("trace_id") in trace_ids):
                        spans.append(r)
        spans.sort(key=lambda r: r.get("ts_us", 0))
    first = {}
    resolves: list = []
    for r in events:  # first occurrence of each lifecycle stage wins...
        if r.get("event") == "resolve":
            resolves.append(r)
            continue
        first.setdefault(r.get("event"), r.get("ts_us", 0))
    # ...except resolve: a fleet-routed uuid resolves a replica-level
    # future per attempt (a killed replica's typed rejection, a hedge
    # loser) before the ROUTER future settles — the terminal resolve is
    # the one tagged scope=fleet when present, else the last one seen
    # (plain single-server timelines have exactly one either way)
    if resolves:
        tagged = [r for r in resolves
                  if (r.get("attrs") or {}).get("scope")]
        first["resolve"] = (tagged[-1] if tagged
                            else resolves[-1]).get("ts_us", 0)
    phases = {}

    def _ms(a, b):
        return round((first[b] - first[a]) / 1e3, 3)

    if "enqueue" in first and "admit" in first:
        phases["queue_ms"] = _ms("enqueue", "admit")
    if "admit" in first:
        if "finish" in first:
            phases["resident_ms"] = _ms("admit", "finish")
        elif "resolve" in first:
            phases["resident_ms"] = _ms("admit", "resolve")
    if "finish" in first and "resolve" in first:
        phases["resolve_ms"] = _ms("finish", "resolve")
    # a request's timeline ROOT is its first lifecycle event: enqueue
    # for a queued request, else coalesced (a follower attached to an
    # in-flight leader) or cache_hit (resolved synchronously at submit)
    # — the ISSUE-14 front-door paths never enqueue (SERVING.md "Front
    # door"), but their coalesced/cache_hit -> resolve window is still
    # the caller-observed total
    root = next((e for e in ("enqueue", "coalesced", "cache_hit")
                 if e in first), None)
    if root is not None and "resolve" in first:
        phases["total_ms"] = _ms(root, "resolve")
    # the hier fan-out tree: a document parent's chunk/reduce
    # sub-requests ride the SAME trace_id under their own uuids, each
    # self-identifying with a hier_chunk/hier_reduce event — group the
    # trace's OTHER uuids and keep exactly those (a hedged or
    # fleet-routed plain request re-emits under its own uuid and is
    # never mistaken for a child)
    children: list = []
    if trace_ids:
        by_uuid: dict = defaultdict(list)
        for path in paths:
            for r in _iter_jsonl(path):
                if (r.get("kind") == "request"
                        and r.get("trace_id") in trace_ids
                        and r.get("uuid") not in (uuid, None, "")):
                    by_uuid[r["uuid"]].append(r)
        for child_uuid, evs in by_uuid.items():
            evs.sort(key=lambda r: r.get("ts_us", 0))
            hier = next((r for r in evs if r.get("event")
                         in ("hier_chunk", "hier_reduce")), None)
            if hier is None:
                continue
            attrs = hier.get("attrs") or {}
            cfirst: dict = {}
            for r in evs:
                cfirst.setdefault(r.get("event"), r.get("ts_us", 0))
            resident = None
            if "admit" in cfirst:
                end = cfirst.get("finish", cfirst.get("resolve"))
                if end is not None:
                    resident = round((end - cfirst["admit"]) / 1e3, 3)
            children.append({
                "uuid": child_uuid,
                "kind": ("reduce" if hier.get("event") == "hier_reduce"
                         else "chunk"),
                "chunk": attrs.get("chunk"),
                "bucket": attrs.get("bucket"),
                "tier": attrs.get("tier"),
                "cache_hit": bool(attrs.get("cache_hit")),
                "resident_ms": resident,
            })
        children.sort(key=lambda c: (c["kind"] == "reduce",
                                     c["chunk"] if c["chunk"] is not None
                                     else 1 << 30, c["uuid"]))
    return {"uuid": uuid, "trace_id": trace_id, "events": events,
            "spans": spans, "phases": phases,
            "children": children, "trace_ids": sorted(trace_ids)}


def print_request_timeline(tl: dict) -> int:
    if not tl["events"]:
        print(f"no request events for uuid {tl['uuid']!r} — was the run "
              f"writing a unified events.jsonl (obs.install_event_sink / "
              f"TS_OBS_EVENTS=1, OBSERVABILITY.md)?", file=sys.stderr)
        return 1
    print(f"request {tl['uuid']!r} (trace {tl['trace_id']}):")
    t0 = tl["events"][0].get("ts_us", 0)
    for r in tl["events"]:
        attrs = r.get("attrs") or {}
        extra = (" (" + ", ".join(f"{k}={v}" for k, v in attrs.items())
                 + ")") if attrs else ""
        print(f"  +{(r.get('ts_us', 0) - t0) / 1e3:>9.3f} ms "
              f"{r.get('event')}{extra}")
    if tl["phases"]:
        print("phases: " + " | ".join(
            f"{k[:-3]} {v:.3f} ms" for k, v in tl["phases"].items()))
    if tl.get("children"):
        kids = tl["children"]
        n_chunks = sum(1 for c in kids if c["kind"] == "chunk")
        n_red = len(kids) - n_chunks
        print(f"fan-out ({n_chunks} chunk{'s' if n_chunks != 1 else ''}"
              + (f" + {n_red} reduce" if n_red else "") + "):")
        for i, c in enumerate(kids):
            branch = "└─" if i == len(kids) - 1 else "├─"
            label = (f"reduce" if c["kind"] == "reduce"
                     else f"chunk {c['chunk']}")
            cost = ("cache hit" if c["cache_hit"]
                    else (f"resident {c['resident_ms']:.3f} ms"
                          if c["resident_ms"] is not None else "pending"))
            detail = ", ".join(
                x for x in (f"bucket {c['bucket']}"
                            if c["bucket"] is not None else "",
                            f"tier {c['tier']}" if c["tier"] else "",
                            cost) if x)
            print(f"  {branch} {c['uuid']}  {label}  ({detail})")
    if tl["spans"]:
        print(f"spans in trace ({len(tl['spans'])}):")
        for s in tl["spans"]:
            print(f"  +{(s.get('ts_us', 0) - t0) / 1e3:>9.3f} ms "
                  f"{s.get('name')} ({s.get('dur_us', 0) / 1e3:.3f} ms)")
    if len(tl["trace_ids"]) > 1:
        print(f"WARNING: uuid maps to {len(tl['trace_ids'])} trace_ids "
              f"(resubmitted uuid?): {tl['trace_ids']}", file=sys.stderr)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir", nargs="?", default="exp/trace_r05")
    ap.add_argument("--top", type=int, default=15)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--host-frames", action="store_true",
                    help="keep $file:line python-frame events")
    ap.add_argument("--request", metavar="UUID_OR_TRACE_ID", default=None,
                    help="reconstruct ONE request's lifecycle timeline "
                         "(enqueue->admit->slot->finish->resolve) from "
                         "unified events.jsonl instead of the op table; "
                         "accepts a uuid or a trace_id (e.g. a histogram "
                         "exemplar off /metrics or /exemplars)")
    args = ap.parse_args(argv)

    if args.request is not None:
        jsonl = [p for p in find_trace_files(args.trace_dir)
                 if p.endswith(".jsonl")]
        if not jsonl:
            # a directory holding profiler captures only: look for the
            # events.jsonl family explicitly (request events live there)
            jsonl = sorted(glob.glob(
                os.path.join(args.trace_dir, "**", "events.jsonl"),
                recursive=True)) if os.path.isdir(args.trace_dir) else []
        if not jsonl:
            print(f"no events.jsonl under {args.trace_dir} — request "
                  f"timelines need the unified event stream "
                  f"(OBSERVABILITY.md)", file=sys.stderr)
            return 1
        tl = request_timeline(jsonl, args.request)
        if args.json:
            print(json.dumps(tl))
            return 0 if tl["events"] else 1
        return print_request_timeline(tl)

    files = find_trace_files(args.trace_dir)
    if not files:
        print(f"no *.trace.json[.gz] or events.jsonl under "
              f"{args.trace_dir} — capture a profiler trace in a tunnel "
              f"window (scripts/capture_window_extras.sh) or run with obs "
              f"enabled (OBSERVABILITY.md)", file=sys.stderr)
        return 1
    path = files[-1]  # newest capture wins (sorted paths are dated)
    lanes = summarize(load_events(path), args.host_frames)
    if args.json:
        print(json.dumps({"trace": path, "lanes": [
            {**lane, "ops": lane["ops"][:args.top]} for lane in lanes]}))
        return 0
    print(f"trace: {path}")
    for lane in lanes:
        if not lane["ops"]:
            continue
        print(f"\nlane {lane['lane']!r} (pid {lane['pid']} "
              f"tid {lane['tid']}, busy {lane['busy_us'] / 1e3:.1f} ms):")
        for op in lane["ops"][:args.top]:
            pct = 100.0 * op["total_us"] / max(lane["busy_us"], 1e-9)
            print(f"  {op['total_us'] / 1e3:>9.2f} ms {pct:>5.1f}%  "
                  f"x{op['count']:<5} {op['name'][:80]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
