#!/usr/bin/env python
"""Summarize a trace capture into the op-level table BASELINE.md's
arbitration asks for (top ops by device time, per lane).

    python scripts/trace_summary.py [exp/trace_r05] [--top 15] [--json]
    python scripts/trace_summary.py logs/exp/train/events.jsonl

Two capture kinds, one tool (ISSUE 1 satellite):

  * Chrome-trace JSON (`*.trace.json[.gz]`) that `jax.profiler` writes
    next to the xplane file (TensorBoard not required — the rig has no
    tensorboard_plugin_profile, so this parses the portable format);
  * the unified obs `events.jsonl` (obs/export.py EventSink +
    SummaryWriter scalars in one file): `{"kind": "span", ...}` records
    are treated as complete events; scalar/step records are skipped.

Events are grouped into lanes (one per process/pid: TPU device lanes,
host threads); within a lane, complete events ('ph': 'X') are summed by
name.  Python host-frame events (names like `$threading.py:323 wait`)
are dropped from per-op tables by default — on a device lane the names
are XLA ops/fusions, which is the table that names the bottleneck op
(e.g. the transformer <6%-MFU escalation in BASELINE.md).

Directory arguments prefer profiler captures when both kinds are
present (the established behavior); point at the events.jsonl file
directly — or a directory holding only events.jsonl — for span tables.
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
from collections import defaultdict


def find_trace_files(root: str) -> list:
    """Candidate captures under `root`: profiler Chrome traces when any
    exist (established behavior), else unified obs events.jsonl files."""
    if os.path.isfile(root):
        return [root]
    pats = [os.path.join(root, "**", "*.trace.json.gz"),
            os.path.join(root, "**", "*.trace.json")]
    files: list = []
    for p in pats:
        files.extend(glob.glob(p, recursive=True))
    if files:
        return sorted(files)
    return sorted(glob.glob(os.path.join(root, "**", "events.jsonl"),
                            recursive=True))


def _events_jsonl_to_trace(path: str) -> dict:
    """Unified events.jsonl -> the Chrome-trace dict shape summarize()
    consumes.  Span records become 'X' complete events; SummaryWriter
    scalar records ({"step": N, ...}) and snapshot dumps are skipped."""
    events: list = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # half-written tail line of a live run
            if not isinstance(rec, dict) or rec.get("kind") != "span":
                continue
            events.append({
                "ph": "X",
                "name": rec.get("name", "?"),
                "ts": float(rec.get("ts_us", 0)),
                "dur": float(rec.get("dur_us", 0)),
                "pid": rec.get("pid", 0),
                "tid": rec.get("tid", 0),
            })
    return {"traceEvents": events}


def load_events(path: str) -> dict:
    if path.endswith(".jsonl"):
        return _events_jsonl_to_trace(path)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def summarize(trace: dict, include_host_frames: bool = False) -> list:
    """Per-lane op-time summary, one lane per (pid, tid) thread line.

    Grouping by pid alone would double-count: the profiler's export
    gives a device several lines (e.g. a module/step-level line whose
    events span the same wall time as the per-op line), so summing
    across a pid's tids inflates busy time and the enclosing module
    event would top the \"op\" table.  Per-thread lanes keep each line
    honest; the op line is the one whose names are XLA ops/fusions.

    Returns [{lane, pid, tid, busy_us, ops: [{name, total_us, count}]}]
    sorted by lane busy time, descending.
    """
    proc_names: dict = {}
    thread_names: dict = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            proc_names[e.get("pid")] = e.get("args", {}).get("name", "?")
        elif e.get("name") == "thread_name":
            thread_names[(e.get("pid"), e.get("tid"))] = (
                e.get("args", {}).get("name", "?"))

    per_lane: dict = defaultdict(lambda: defaultdict(lambda: [0.0, 0]))
    busy: dict = defaultdict(float)
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        name = e.get("name", "?")
        if not include_host_frames and name.startswith("$"):
            continue  # python host frames, not ops
        dur = float(e.get("dur", 0.0))
        key = (e.get("pid"), e.get("tid"))
        cell = per_lane[key][name]
        cell[0] += dur
        cell[1] += 1
        busy[key] += dur
    out = []
    for (pid, tid), ops in per_lane.items():
        proc = proc_names.get(pid, str(pid))
        thread = thread_names.get((pid, tid))
        out.append({
            "lane": f"{proc}/{thread}" if thread else proc,
            "pid": pid,
            "tid": tid,
            "busy_us": round(busy[(pid, tid)], 1),
            "ops": sorted(
                ({"name": n, "total_us": round(t, 1), "count": c}
                 for n, (t, c) in ops.items()),
                key=lambda o: -o["total_us"]),
        })
    out.sort(key=lambda lane: -lane["busy_us"])
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir", nargs="?", default="exp/trace_r05")
    ap.add_argument("--top", type=int, default=15)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--host-frames", action="store_true",
                    help="keep $file:line python-frame events")
    args = ap.parse_args(argv)

    files = find_trace_files(args.trace_dir)
    if not files:
        print(f"no *.trace.json[.gz] or events.jsonl under "
              f"{args.trace_dir} — capture a profiler trace in a tunnel "
              f"window (scripts/capture_window_extras.sh) or run with obs "
              f"enabled (OBSERVABILITY.md)", file=sys.stderr)
        return 1
    path = files[-1]  # newest capture wins (sorted paths are dated)
    lanes = summarize(load_events(path), args.host_frames)
    if args.json:
        print(json.dumps({"trace": path, "lanes": [
            {**lane, "ops": lane["ops"][:args.top]} for lane in lanes]}))
        return 0
    print(f"trace: {path}")
    for lane in lanes:
        if not lane["ops"]:
            continue
        print(f"\nlane {lane['lane']!r} (pid {lane['pid']} "
              f"tid {lane['tid']}, busy {lane['busy_us'] / 1e3:.1f} ms):")
        for op in lane["ops"][:args.top]:
            pct = 100.0 * op["total_us"] / max(lane["busy_us"], 1e-9)
            print(f"  {op['total_us'] / 1e3:>9.2f} ms {pct:>5.1f}%  "
                  f"x{op['count']:<5} {op['name'][:80]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
