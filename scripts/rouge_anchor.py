"""ROUGE-vs-anchor harness: decode the CNN/DM test split with the imported
pretrained checkpoint and compare against the See et al. paper numbers.

The anchor is the ACL-2017 pointer-generator+coverage result the reference
points at (~39.53 / 17.28 / 36.38 ROUGE-1/2/L F1; pointer-generator
README "Looking for pretrained model?" note, data/cnn-dailymail/README.md:1
paper link) — the published checkpoint itself scores "slightly lower".

Requires the real artifacts (fetched via scripts/download_data.sh and
scripts/download_model.sh):

  python scripts/rouge_anchor.py \
      --bundle log/pretrained_model_tf1.2.1/model-238410 \
      --data 'data/cnn-dailymail/finished_files/chunked/test_*' \
      --vocab data/cnn-dailymail/finished_files/vocab \
      [--log_root /tmp/rouge_run] [--max_articles N]

Exits 0 when ROUGE-L F1 is within --tolerance (default 0.5 points) of the
anchor, 1 otherwise; always prints one JSON line with the scores.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

ANCHOR = {"rouge_1": 39.53, "rouge_2": 17.28, "rouge_l": 36.38}


def main(argv=None) -> int:
    from textsummarization_on_flink_tpu.checkpoint import tf1_import
    from textsummarization_on_flink_tpu.config import HParams
    from textsummarization_on_flink_tpu.data.batcher import Batcher
    from textsummarization_on_flink_tpu.data.vocab import Vocab
    from textsummarization_on_flink_tpu.decode.decoder import BeamSearchDecoder

    ap = argparse.ArgumentParser()
    ap.add_argument("--bundle", required=True,
                    help="TF1 checkpoint prefix (pretrained_model_tf1.2.1)")
    ap.add_argument("--data", required=True,
                    help="chunked test-split glob (test_*.bin)")
    ap.add_argument("--vocab", required=True)
    ap.add_argument("--log_root", default="/tmp/rouge_anchor")
    ap.add_argument("--max_articles", type=int, default=0,
                    help="0 = the full 11,490-article test split")
    ap.add_argument("--tolerance", type=float, default=0.5)
    args = ap.parse_args(argv)

    train_dir = os.path.join(args.log_root, "anchor", "train")
    print(f"importing {args.bundle} -> {train_dir}", file=sys.stderr)
    tf1_import.import_to_train_dir(args.bundle, train_dir)

    hps = HParams(mode="decode", single_pass=True, coverage=True,
                  data_path=args.data, vocab_path=args.vocab,
                  log_root=args.log_root, exp_name="anchor",
                  batch_size=16)
    vocab = Vocab(hps.vocab_path, hps.vocab_size)
    batcher = Batcher(hps.data_path, vocab, hps, single_pass=True,
                      decode_batch_mode="distinct")
    decoder = BeamSearchDecoder(hps, vocab, batcher, train_dir=train_dir)
    max_batches = (-(-args.max_articles // hps.batch_size)
                   if args.max_articles else 0)
    results = decoder.decode(with_rouge=True, max_batches=max_batches)
    if results is None:
        print(json.dumps({"error": "decode produced no ROUGE results"}))
        return 1

    scores = {k: round(results[k]["f_score"] * 100, 2)
              for k in ("rouge_1", "rouge_2", "rouge_l")}
    delta = {k: round(scores[k] - ANCHOR[k], 2) for k in scores}
    ok = abs(delta["rouge_l"]) <= args.tolerance or \
        delta["rouge_l"] > 0  # beating the anchor is never a failure
    print(json.dumps({"metric": "rouge_vs_anchor", "scores": scores,
                      "anchor": ANCHOR, "delta": delta, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
