"""Front-door smoke (ISSUE 14): a duplicate-heavy request mix through
a real tiny model with coalescing + the summary cache armed — the
no-hardware proof that the production front door works end to end:

  * a burst of identical articles submitted together COALESCES onto one
    decode (``serve/coalesced_total`` > 0) and every future resolves
    exactly once with its own uuid;
  * a second pass over the same articles is served from the CACHE
    (``serve/cache_hits_total``; zero new decodes) with each hit row
    byte-identical to its original decode — the pointer-generator's
    deterministic tiers are what make the reuse exact;
  * a third pass at a DIFFERENT tier misses (the tier is part of the
    key) and decodes fresh.

The committed scheduling claims (zipf decode ratio, p99, tenant
isolation, fleet composition) live in SERVE_SLO.json "front_door" and
are enforced by tests/test_serve_slo.py over virtual time; this smoke
proves the THREADED path on a real model.  Wired into
scripts/repro.sh.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import tempfile  # noqa: E402

from textsummarization_on_flink_tpu import obs  # noqa: E402
from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.vocab import Vocab  # noqa: E402
from textsummarization_on_flink_tpu.serve.server import (  # noqa: E402
    ServingServer,
)
from textsummarization_on_flink_tpu.train import trainer  # noqa: E402


def main() -> None:
    # duplicate-heavy mix: 12 requests over 3 DISTINCT articles
    distinct = ["article 0 .",
                "article 1 " + ". article " * 5 + ".",
                "article 2 article 0 ."]
    requests = [(f"uuid-{i}", distinct[i % 3]) for i in range(12)]
    vocab = Vocab(words=["article", "reference", ".", "0", "1", "2"])
    hps = HParams(mode="decode", batch_size=2, hidden_dim=16, emb_dim=8,
                  vocab_size=vocab.size(), max_enc_steps=16,
                  max_dec_steps=6, beam_size=2, min_dec_steps=1,
                  max_oov_buckets=4, serve_max_wait_ms=50.0,
                  serve_max_queue=64, serve_buckets="8,16",
                  serve_coalesce=True, serve_cache_entries=32)
    params = trainer.init_train_state(hps, vocab.size(), seed=0).params
    reg = obs.registry()
    server = ServingServer(
        hps, vocab, params=params,
        decode_root=tempfile.mkdtemp(prefix="front_door_smoke_"))
    with server:
        # pass 1: the burst — duplicates in flight together coalesce
        futs = [server.submit(a, uuid=u) for u, a in requests]
        rows1 = {u: f.result(timeout=600).as_row() for (u, _), f
                 in zip(requests, futs)}
        decodes1 = int(reg.counter("serve/completed_total").value)
        coalesced = int(reg.counter("serve/coalesced_total").value)
        assert sorted(rows1) == sorted(u for u, _ in requests)
        assert coalesced > 0, (
            "no submits coalesced — the burst never shared a decode")
        assert decodes1 + coalesced + int(
            reg.counter("serve/cache_hits_total").value) == len(requests)
        # same article => byte-identical summary, whatever the uuid
        by_article = {}
        for (u, a), _ in zip(requests, futs):
            by_article.setdefault(a, set()).add(rows1[u][2])
        assert all(len(s) == 1 for s in by_article.values()), by_article

        # pass 2: the cache — zero new decodes, rows byte-identical to
        # the original decode (the row-parity pin)
        futs2 = [server.submit(a, uuid=u + "-again") for u, a in requests]
        rows2 = [f.result(timeout=600).as_row() for f in futs2]
        decodes2 = int(reg.counter("serve/completed_total").value)
        hits = int(reg.counter("serve/cache_hits_total").value)
        assert decodes2 == decodes1, (
            f"warm pass decoded ({decodes2 - decodes1} new decodes)")
        assert hits >= len(requests), hits
        for (u, a), row in zip(requests, rows2):
            assert row[0] == u + "-again"
            assert row[2] == rows1[u][2], (
                f"cache hit row for {a!r} drifted from its original "
                f"decode")

        # pass 3: a different tier is a different key — fresh decodes
        fut3 = server.submit(distinct[0], uuid="greedy-0", tier="greedy")
        fut3.result(timeout=600)
        assert int(reg.counter("serve/completed_total").value) \
            == decodes2 + 1, "a new tier must miss and decode"

    age = reg.histogram("serve/cache_entry_age_seconds")
    print(f"front-door smoke OK: {len(requests)} duplicate-heavy "
          f"requests -> {decodes1} decodes ({coalesced} coalesced), "
          f"warm pass {hits} cache hits / 0 decodes with byte-identical "
          f"rows, tier axis missed as designed "
          f"(entries {int(reg.gauge('serve/cache_entries').value)}, "
          f"mean hit age {age.mean * 1000:.1f} ms)")


if __name__ == "__main__":
    main()
